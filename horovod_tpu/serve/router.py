"""Request routing over the elastic worker registry.

The router is the traffic-direction half of the serving plane: it holds the
live worker table (fed from the elastic rendezvous KV, where serve workers
publish their HTTP endpoints and the driver aggregates them into the
``serve_targets`` key each heartbeat), places each request on the
least-loaded accepting worker, and enforces the serving plane's central
durability contract:

    **an accepted request is never silently lost.**

Concretely:

- a worker absent from a new generation is *drained* — no new placements,
  in-flight requests get ``HOROVOD_SERVE_DRAIN_TIMEOUT_SECONDS`` to finish
  on the departing worker before the router re-routes them;
- a worker that *dies* (connection refused / reset mid-request) is marked
  dead immediately and the failed dispatch is retried on a surviving
  worker, up to ``HOROVOD_SERVE_RETRY_LIMIT`` times; only an exhausted
  retry budget surfaces an error to the caller (loud, counted in
  ``hvd_serve_lost_total`` — which a healthy cluster keeps at zero);
- generation changes (elastic resize) swap the worker table atomically:
  re-registered workers keep serving, new ones join the rotation, departed
  ones drain.

Transport is pluggable: :meth:`RequestRouter.submit` takes a ``send``
callable, so tests drive routing with in-process functions and production
uses :func:`post_json` against worker frontends.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional
from urllib import error as urlerror
from urllib import request as urlrequest

from horovod_tpu.common import journal
from horovod_tpu.common.env_registry import env_int
from horovod_tpu.common.hvd_logging import get_logger
from horovod_tpu.metrics.registry import MetricsRegistry, get_registry
from horovod_tpu.obs.tracing import RE_ROUTE, get_tracer, now_us

UP = "up"
DRAINING = "draining"
DEAD = "dead"


class NoWorkersError(RuntimeError):
    """No accepting worker is registered (all dead/draining or none yet)."""


def post_json(addr: str, port: int, path: str, payload: dict,
              timeout: float = 30.0) -> dict:
    """POST a JSON body, return the decoded JSON response.

    Only *transport* failures raise (connection refused/reset, timeout —
    the router's he's-dead retry path). An HTTP error status means the
    worker answered — a 429 is backpressure from a live worker, not a
    death — so its JSON body is returned like any other response and the
    ``status`` field carries the verdict."""
    body = json.dumps(payload).encode()
    req = urlrequest.Request(f"http://{addr}:{port}{path}", data=body,
                             method="POST",
                             headers={"Content-Type": "application/json"})
    try:
        with urlrequest.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())
    except urlerror.HTTPError as e:
        return json.loads(e.read())


class WorkerHandle:
    """One registered serving worker as the router sees it."""

    __slots__ = ("id", "addr", "port", "rank", "generation", "state",
                 "inflight")

    def __init__(self, id: str, addr: str, port: int, rank: Optional[int],
                 generation: int):
        self.id = id
        self.addr = addr
        self.port = int(port)
        self.rank = rank
        self.generation = generation
        self.state = UP
        self.inflight: set = set()

    @property
    def accepting(self) -> bool:
        return self.state == UP

    def describe(self) -> dict:
        return {"id": self.id, "addr": self.addr, "port": self.port,
                "rank": self.rank, "generation": self.generation,
                "state": self.state, "inflight": len(self.inflight)}


class RequestRouter:
    def __init__(self, retry_limit: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.retry_limit = retry_limit if retry_limit is not None \
            else env_int("HOROVOD_SERVE_RETRY_LIMIT")
        self._lock = threading.Lock()
        self._workers: Dict[str, WorkerHandle] = {}
        self.generation = -1
        # control-plane outage state: when discovery (the driver's
        # serve_targets key) disappears, the router keeps serving from
        # this last-known table, marked stale, instead of draining a
        # fleet of healthy workers over a dead metadata service
        self.discovery_stale = False
        self._last_refresh: Optional[float] = None  # monotonic
        self._log = get_logger("serve.router")
        reg = registry if registry is not None else get_registry()
        self._routed = reg.counter("hvd_serve_routed_total")
        self._rerouted = reg.counter("hvd_serve_rerouted_total")
        self._lost = reg.counter("hvd_serve_lost_total")
        self._workers_up = reg.gauge("hvd_serve_workers_up")

    # -- registry maintenance -----------------------------------------------

    def update_workers(self, entries: List[dict], generation: int):
        """Install the worker set of ``generation``. Entries:
        ``{"id", "addr", "port", "rank"?, "draining"?}``. Workers absent
        from the new set begin draining (their in-flight requests finish
        or get re-routed by their own dispatch threads); dead ones stay
        dead. An entry flagged ``draining`` (the driver's scale-down
        announce) stops taking NEW placements *immediately* — before,
        placement only reacted once the worker left the table, so every
        refresh-to-removal window placed fresh requests onto a worker
        already told to die."""
        with self._lock:
            seen = set()
            for e in entries:
                wid = str(e.get("id") or f"{e['addr']}:{e['port']}")
                # `or` would coerce an explicit generation 0 to the table
                # generation and revive a gen-0 corpse from its own stale
                # record — only a MISSING field inherits the table's
                eg = e.get("generation")
                entry_gen = int(eg) if eg is not None else int(generation)
                seen.add(wid)
                w = self._workers.get(wid)
                if w is None:
                    self._workers[wid] = w = WorkerHandle(
                        wid, e["addr"], e["port"], e.get("rank"),
                        entry_gen)
                else:
                    w.addr, w.port = e["addr"], int(e["port"])
                    w.rank = e.get("rank", w.rank)
                    if w.state == DRAINING and not e.get("draining"):
                        # re-registered without the flag: it stayed (a
                        # still-flagged entry keeps draining without a
                        # churny DRAINING->UP->DRAINING flip per refresh)
                        w.state = UP
                    elif w.state == DEAD and entry_gen > w.generation:
                        # a respawned slot reuses its id: only a STRICTLY
                        # newer registration revives it — the dead
                        # worker's stale KV record (same generation)
                        # must not resurrect a corpse into the rotation
                        w.state = UP
                        w.inflight.clear()
                    w.generation = max(w.generation, entry_gen)
                if e.get("draining") and w.state == UP:
                    w.state = DRAINING
                    self._log.info(
                        "worker %s announced draining (scale-down): no "
                        "new placements (%d in flight)", wid,
                        len(w.inflight))
            for wid_, w_ in list(self._workers.items()):
                if wid_ not in seen:
                    if w_.state == UP:
                        w_.state = DRAINING
                        self._log.info(
                            "worker %s absent from generation %d: draining "
                            "(%d in flight)", wid_, generation,
                            len(w_.inflight))
                    if not w_.inflight and w_.state == DRAINING:
                        del self._workers[wid_]
            self.generation = generation
            self._refresh_gauge_locked()

    def refresh_from_kv(self, kv_get_json: Callable[[str], Optional[dict]]
                        ) -> bool:
        """Pull the driver-published ``serve_targets`` key (same pattern as
        ``hvd-top``'s ``metrics_targets``) and install it. ``kv_get_json``
        is any ``key -> dict|None`` getter (KVServer.get_json,
        KVClient.get_json).

        Returns True on a successful refresh. A discovery outage (KV
        unreachable, key gone) keeps the last-known table and flips
        :attr:`discovery_stale` — surfaced in ``/stats`` — rather than
        draining workers that are still answering requests."""
        try:
            from horovod_tpu.common import kv_keys
            info = kv_get_json(kv_keys.serve_targets())
        except Exception:  # noqa: BLE001 — KV mid-restart is an outage,
            info = None  # not a router crash
        if not isinstance(info, dict) or "workers" not in info:
            # "stale" means a previously-working discovery went away; a
            # router that has never refreshed (driver still publishing
            # its first table) is merely warming up, not degraded
            if self._last_refresh is not None:
                if not self.discovery_stale:
                    self._log.warning(
                        "serve discovery unreachable: %s",
                        json.dumps({"event": "discovery_stale",
                                    "workers": len(self._workers),
                                    "generation": self.generation}))
                    journal.emit("serve", "discovery_stale",
                                 generation=self.generation,
                                 workers=len(self._workers))
                self.discovery_stale = True
            return False
        self.update_workers(info["workers"],
                            int(info.get("generation", 0)))
        if self.discovery_stale:
            self._log.info("serve discovery recovered (generation %d)",
                           self.generation)
            journal.emit("serve", "discovery_recovered",
                         generation=self.generation,
                         workers=len(self._workers))
        self.discovery_stale = False
        self._last_refresh = time.monotonic()
        return True

    @property
    def discovery_age_seconds(self) -> Optional[float]:
        """Seconds since the last successful discovery refresh (None
        before the first one)."""
        if self._last_refresh is None:
            return None
        return time.monotonic() - self._last_refresh

    def stale_info(self) -> dict:
        """Discovery-health summary for ``/stats`` consumers."""
        age = self.discovery_age_seconds
        return {"discovery_stale": self.discovery_stale,
                "discovery_age_seconds":
                    round(age, 3) if age is not None else None,
                "generation": self.generation,
                "workers": len(self._workers)}

    def fail_worker(self, worker_id: str) -> List[str]:
        """Mark a worker dead; returns the request ids that were in flight
        on it (each owning dispatch thread re-routes its own)."""
        with self._lock:
            w = self._workers.get(worker_id)
            if w is None:
                return []
            w.state = DEAD
            orphans = list(w.inflight)
            w.inflight.clear()
            self._refresh_gauge_locked()
        if orphans:
            self._log.warning("worker %s died with %d request(s) in "
                              "flight; re-routing", worker_id, len(orphans))
        journal.emit("serve", "worker_failed", generation=self.generation,
                     worker=worker_id, orphans=len(orphans))
        return orphans

    def drain_worker(self, worker_id: str) -> List[str]:
        """Administrative drain: stop new placements, report in-flight."""
        with self._lock:
            w = self._workers.get(worker_id)
            if w is None:
                return []
            if w.state == UP:
                w.state = DRAINING
            self._refresh_gauge_locked()
            return list(w.inflight)

    def workers(self) -> List[dict]:
        with self._lock:
            return [w.describe() for w in self._workers.values()]

    def _refresh_gauge_locked(self):
        self._workers_up.set(
            sum(1 for w in self._workers.values() if w.accepting))

    # -- placement -----------------------------------------------------------

    def pick(self, exclude: Optional[set] = None) -> WorkerHandle:
        """Least-loaded accepting worker (ties by id for determinism)."""
        with self._lock:
            candidates = [w for w in self._workers.values()
                          if w.accepting and
                          (not exclude or w.id not in exclude)]
            if not candidates:
                raise NoWorkersError(
                    "no accepting serving worker registered")
            return min(candidates, key=lambda w: (len(w.inflight), w.id))

    def assign(self, worker: WorkerHandle, request_id: str):
        with self._lock:
            worker.inflight.add(request_id)

    def complete(self, worker: WorkerHandle, request_id: str):
        with self._lock:
            worker.inflight.discard(request_id)
            if worker.state == DRAINING and not worker.inflight:
                self._workers.pop(worker.id, None)
                self._log.info("worker %s fully drained", worker.id)

    def submit(self, request_id: str, payload: dict,
               send: Callable[[WorkerHandle, dict], dict]) -> dict:
        """Dispatch with the no-silent-loss contract: pick → send; a
        transport failure marks the worker dead and retries on a survivor
        (``hvd_serve_rerouted_total``), up to ``retry_limit`` extra
        attempts. Exhaustion raises — counted in ``hvd_serve_lost_total``,
        which a healthy cluster pins at zero."""
        last: Optional[Exception] = None
        tried: set = set()
        trace = payload.get("trace")
        tid = trace.get("id") if isinstance(trace, dict) else trace or None
        for attempt in range(self.retry_limit + 1):
            try:
                worker = self.pick(exclude=tried)
            except NoWorkersError:
                # every known worker already failed this request — widen
                # back out in case a replacement registered meanwhile
                try:
                    worker = self.pick()
                except NoWorkersError:
                    break
            self.assign(worker, request_id)
            t0 = now_us()
            try:
                resp = send(worker, payload)
            except Exception as e:  # noqa: BLE001 — transport failure is
                # the retry path, not a crash
                last = e
                tried.add(worker.id)
                self.fail_worker(worker.id)
                if attempt < self.retry_limit:
                    self._rerouted.inc()
                    journal.emit("serve", "re_route", trace_id=tid,
                                 request_id=request_id,
                                 failed_worker=worker.id, attempt=attempt)
                    # span covers the failed dispatch attempt — the time
                    # the re-route decision cost this request
                    get_tracer().record(
                        tid, RE_ROUTE, "router", t0, now_us() - t0,
                        failed_worker=worker.id, attempt=attempt,
                        error=repr(e))
                continue
            self.complete(worker, request_id)
            self._routed.inc()
            return resp
        self._lost.inc()
        raise NoWorkersError(
            f"request {request_id} failed after {self.retry_limit + 1} "
            f"attempt(s): {last!r}")
