"""Online inference serving plane (docs/DESIGN.md "Serving plane").

Everything through PR 7 served the *training* half of the north star; this
package is the "heavy traffic from millions of users" half, built on the
same engine, elastic runner, and metrics stack:

- :mod:`~horovod_tpu.serve.batcher` — continuous-batching admission:
  bounded queue with backpressure, per-request deadlines, length buckets
  shared with the flash-attention router so every batch keeps one static
  shape (and one kernel route) for its whole lifetime;
- :mod:`~horovod_tpu.serve.executor` — the decode loop plus tensor-parallel
  forward passes whose activation reductions ride the EQuARX int8 quantized
  collectives (PR 1 built them for gradients; serving applies them to
  activations);
- :mod:`~horovod_tpu.serve.router` — request routing over the elastic
  rendezvous KV: least-loaded placement, generation-change re-routing, and
  drain-on-death with a no-silent-loss contract for accepted requests;
- :mod:`~horovod_tpu.serve.frontend` — stdlib HTTP ingress (the
  ``metrics/exporter.py`` server pattern): ``POST /v1/generate``,
  ``GET /healthz``, ``GET /stats``;
- :mod:`~horovod_tpu.serve.worker` — the per-process serving worker the
  elastic driver spawns: registers its endpoint in the KV, heartbeats the
  engine with small serving-mode collectives, drains instead of dropping
  on membership changes;
- :mod:`~horovod_tpu.serve.loadgen` — open-loop load generation behind the
  BENCH ``serving`` block (p50/p99 vs offered load) and the small-tensor
  latency microbench;
- :mod:`~horovod_tpu.serve.admission` — SLO-aware admission in front of
  the batcher: priority classes shed lowest-first under queue pressure,
  per-tenant token-bucket quotas 429 with Retry-After — how the fleet
  degrades gracefully while an autoscale resize is in flight;
- :mod:`~horovod_tpu.serve.autoscale_smoke` — the closed loop from
  offered load to fleet size (BENCH ``autoscale`` block,
  ``make autoscale-smoke``): an in-process fleet behind the real router
  driven by the real :mod:`~horovod_tpu.runner.elastic.autoscaler`.

The engine side is ``HOROVOD_SERVING_MODE``: sub-threshold collectives skip
the fusion buffer (they are latency- not bandwidth-bound — the regime the
MPI characterization work, arXiv:1810.11112, shows behaves nothing like
gradient exchange) and the cycle wait is clamped to
``HOROVOD_SERVING_CYCLE_TIME``.
"""

from horovod_tpu.serve.admission import (  # noqa: F401
    AdmissionController,
    TokenBucket,
    parse_priority_classes,
)
from horovod_tpu.serve.batcher import (  # noqa: F401
    AdmissionRejected,
    ContinuousBatcher,
    InferenceRequest,
    bucket_for,
    bucket_plan,
    default_buckets,
)
from horovod_tpu.serve.executor import (  # noqa: F401
    CachedStep,
    ServingLoop,
    activation_wire_report,
    make_rnn_lm_step,
    make_toy_cached_step,
    make_toy_draft_step,
    make_toy_step,
    make_tp_lm_step,
)
from horovod_tpu.serve.kv_cache import (  # noqa: F401
    CacheExhausted,
    CacheLease,
    PagedKVCache,
    blocks_for,
    prefix_hash,
)
from horovod_tpu.serve.frontend import ServeFrontend  # noqa: F401
from horovod_tpu.serve.router import (  # noqa: F401
    NoWorkersError,
    RequestRouter,
    WorkerHandle,
)
