"""SLO-aware admission: priority classes + per-tenant token-bucket quotas.

The bounded admission queue (serve/batcher.py) is a blunt instrument: past
saturation it rejects whoever arrives next, premium traffic included. This
module is the graded version the autoscaler needs while a resize is in
flight — the fleet degrades *by class* instead of by arrival order:

- **priority classes** (``HOROVOD_SERVE_PRIORITY_CLASSES``, lowest first):
  each class may enter only while the admission queue is under its fill
  threshold. With classes ``c_0..c_{L-1}`` class ``c_l`` admits while
  ``queue_fill < (l+1)/L`` — so as pressure builds the lowest class is
  shed first and the queue's top slice stays reserved for the highest,
  which is only ever rejected by the bounded queue itself. A request
  names its class in the body (``"priority": "premium"``); an *unknown*
  name is treated as the lowest class (a typo must not accidentally gain
  priority), a *missing* one as the highest (unclassified traffic keeps
  the pre-classes behavior: shed only by the full queue).
- **per-tenant quotas**: a token bucket per ``"tenant"`` body field
  (rate ``HOROVOD_SERVE_TENANT_QPS``, burst ``HOROVOD_SERVE_TENANT_BURST``);
  an exhausted tenant gets a 429 with ``Retry-After`` telling it exactly
  when one token refills, before the request ever touches the queue.
  Tenant-less requests share no bucket (quotas off for them).

Both checks are *immediate* — the 429 carries ``retry_after_seconds`` and
the frontend surfaces it as a ``Retry-After`` header, so well-behaved
clients back off instead of hammering a saturated fleet. Decisions land in
the shared metrics registry (``hvd_serve_admit_total`` /
``hvd_serve_shed_total`` by class, ``hvd_serve_quota_shed_total``), which
is what ``hvd-top --autoscale`` and the BENCH autoscale block read.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, NamedTuple, Optional, Sequence

from horovod_tpu.common.env_registry import env_float, env_str
from horovod_tpu.metrics.registry import MetricsRegistry, get_registry


def parse_priority_classes(spec: Optional[str] = None) -> Dict[str, int]:
    """``{class_name: level}`` from a comma-separated spec, lowest
    priority first (``"batch,standard,premium"`` → batch=0 … premium=2).
    Empty segments are ignored; duplicates keep their first level."""
    if spec is None:
        spec = env_str("HOROVOD_SERVE_PRIORITY_CLASSES")
    out: Dict[str, int] = {}
    for name in (spec or "").split(","):
        name = name.strip()
        if name and name not in out:
            out[name] = len(out)
    if not out:
        out = {"standard": 0}
    return out


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/sec refill, ``burst`` cap.
    ``take()`` returns seconds until one token refills (0.0 = admitted)."""

    __slots__ = ("rate", "burst", "tokens", "_last")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._last = time.monotonic()

    def refill(self, now: Optional[float] = None):
        now = time.monotonic() if now is None else now
        self.tokens = min(self.burst,
                          self.tokens + (now - self._last) * self.rate)
        self._last = now

    @property
    def full(self) -> bool:
        """At burst capacity — an idle tenant whose bucket carries no
        state worth keeping (a fresh bucket is indistinguishable)."""
        return self.tokens >= self.burst

    def take(self, now: Optional[float] = None) -> float:
        self.refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return (1.0 - self.tokens) / self.rate if self.rate > 0 else 60.0


class AdmitResult(NamedTuple):
    ok: bool
    cls: str                     # resolved priority class
    reason: str                  # "" when admitted
    retry_after_seconds: float   # backoff hint for 429 responses


class AdmissionController:
    """Priority-class shedding + tenant quotas in front of the batcher.

    Thread contract: ``admit`` may be called from any number of frontend
    handler threads; the tenant-bucket map is the only mutable state.
    The map is bounded: past :attr:`MAX_TRACKED_TENANTS`, buckets back
    at burst capacity (idle tenants — a fresh bucket is
    indistinguishable) are evicted, so a client rotating tenant ids
    cannot grow the ingress hot path without bound."""

    MAX_TRACKED_TENANTS = 4096

    def __init__(self, classes: Optional[Dict[str, int]] = None,
                 tenant_qps: Optional[float] = None,
                 tenant_burst: Optional[float] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.classes = dict(classes) if classes is not None \
            else parse_priority_classes()
        self.tenant_qps = tenant_qps if tenant_qps is not None \
            else env_float("HOROVOD_SERVE_TENANT_QPS")
        self.tenant_burst = tenant_burst if tenant_burst is not None \
            else env_float("HOROVOD_SERVE_TENANT_BURST")
        self._levels = max(self.classes.values()) + 1
        self._lowest = min(self.classes, key=self.classes.get)
        self._highest = max(self.classes, key=self.classes.get)
        self._buckets: Dict[str, TokenBucket] = {}
        self._lock = threading.Lock()
        reg = registry if registry is not None else get_registry()
        self._admitted = {c: reg.counter("hvd_serve_admit_total",
                                         **{"class": c})
                          for c in self.classes}
        self._shed = {c: reg.counter("hvd_serve_shed_total",
                                     **{"class": c})
                      for c in self.classes}
        self._quota_shed = reg.counter("hvd_serve_quota_shed_total")

    def resolve_class(self, body: dict) -> str:
        name = body.get("priority")
        if name is None:
            return self._highest
        return name if name in self.classes else self._lowest

    def fill_threshold(self, cls: str) -> float:
        """Queue-fill fraction at which ``cls`` starts being shed."""
        return (self.classes[cls] + 1) / self._levels

    def admit(self, body: dict, queue_fill: float) -> AdmitResult:
        """One admission verdict. ``queue_fill`` is the batcher's current
        queue occupancy fraction (pending / queue_depth)."""
        cls = self.resolve_class(body)
        tenant = body.get("tenant")
        if tenant is not None and self.tenant_qps > 0:
            with self._lock:
                bucket = self._buckets.get(str(tenant))
                if bucket is None:
                    if len(self._buckets) >= self.MAX_TRACKED_TENANTS:
                        self._evict_idle_locked()
                    bucket = self._buckets[str(tenant)] = TokenBucket(
                        self.tenant_qps, self.tenant_burst)
                wait = bucket.take()
            if wait > 0:
                self._quota_shed.inc()
                self._shed[cls].inc()
                return AdmitResult(
                    False, cls,
                    f"tenant {tenant} over quota "
                    f"({self.tenant_qps:g} req/s)", round(wait, 3))
        threshold = self.fill_threshold(cls)
        if queue_fill >= threshold:
            self._shed[cls].inc()
            # the backoff hint scales with how far past its threshold the
            # class is — deeper pressure, longer retry
            return AdmitResult(
                False, cls,
                f"class {cls} shed under queue pressure "
                f"(fill {queue_fill:.2f} >= {threshold:.2f})",
                round(0.5 + queue_fill, 3))
        self._admitted[cls].inc()
        return AdmitResult(True, cls, "", 0.0)

    def _evict_idle_locked(self):
        """Drop buckets back at burst capacity (refilled first, so only
        genuinely idle tenants go); recently-active tenants survive.
        Backstop for slow-refill configurations where nothing is full
        yet: drop oldest-inserted buckets down to the cap — a
        rotating-id client gets fresh-burst treatment either way."""
        now = time.monotonic()
        for tenant, bucket in list(self._buckets.items()):
            bucket.refill(now)
            if bucket.full:
                del self._buckets[tenant]
        while len(self._buckets) >= self.MAX_TRACKED_TENANTS:
            self._buckets.pop(next(iter(self._buckets)))

    def counters(self) -> dict:
        """Per-class admit/shed totals (tests + /stats)."""
        return {
            "admitted": {c: m.value for c, m in self._admitted.items()},
            "shed": {c: m.value for c, m in self._shed.items()},
            "quota_shed": self._quota_shed.value,
        }


def controller_from_env(
        registry: Optional[MetricsRegistry] = None) -> AdmissionController:
    """The env-configured controller serve workers install."""
    return AdmissionController(registry=registry)
