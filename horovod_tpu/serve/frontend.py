"""Stdlib HTTP ingress for the serving plane.

Same server pattern as the metrics exporter and the rendezvous KV
(daemonized ``ThreadingHTTPServer``, port 0 for tests). Two modes:

- **local** (``batcher=``): requests are admitted into this process's
  continuous batcher and the handler thread blocks on the request event
  until the serving loop completes it — this is what every serve *worker*
  runs;
- **routed** (``router=``): requests are forwarded to the least-loaded
  registered worker with the router's no-silent-loss retry — this is the
  cluster *ingress* in front of the elastic worker pool.

Routes::

    POST /v1/generate   {"tokens": [...] | "prompt": "text",
                         "max_new_tokens": N, "deadline_ms": D, "id": ...}
        -> 200 {"id", "status": "ok"|"expired"|"failed", "tokens", ...}
        -> 429 on admission rejection (backpressure)
        -> 503 when no worker accepts (routed mode)
    GET /healthz        {"status": "ok"|"draining"}  (503 while draining —
                        load balancers stop sending before the drain ends)
    GET /stats          serving counters + p50/p99 snapshot

``"prompt"`` strings are byte-level tokenized (UTF-8 bytes), which keeps
the demo/example path dependency-free; real deployments submit token ids.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from horovod_tpu.common import journal
from horovod_tpu.metrics import histogram_quantile, snapshot_histogram, \
    snapshot_value
from horovod_tpu.metrics.registry import MetricsRegistry, get_registry
from horovod_tpu.obs.tracing import ADMISSION, get_tracer
from horovod_tpu.serve.admission import AdmissionController
from horovod_tpu.serve.batcher import AdmissionRejected, ContinuousBatcher
from horovod_tpu.serve.router import (NoWorkersError, RequestRouter,
                                      post_json)

# extra grace past the request deadline before the handler gives up on the
# serving loop delivering the completion event (it expires the request at
# the next step boundary, which needs one in-flight step to pass)
_WAIT_SLACK_SEC = 30.0


def serving_stats(snapshot: dict) -> dict:
    """Serving health summary from a ``/metrics.json`` snapshot — shared by
    ``GET /stats``, ``hvd-top --serving`` and the BENCH serving block."""
    lat = snapshot_histogram(snapshot, "hvd_serve_request_latency_seconds")
    occ = snapshot_histogram(snapshot, "hvd_serve_batch_occupancy")
    out = {
        "requests_ok": snapshot_value(snapshot, "hvd_serve_requests_total",
                                      status="ok") or 0,
        "requests_rejected": snapshot_value(
            snapshot, "hvd_serve_requests_total", status="rejected") or 0,
        "requests_expired": snapshot_value(
            snapshot, "hvd_serve_requests_total", status="expired") or 0,
        "requests_failed": snapshot_value(
            snapshot, "hvd_serve_requests_total", status="failed") or 0,
        "queue_depth": snapshot_value(snapshot, "hvd_serve_queue_depth"),
        "inflight": snapshot_value(snapshot, "hvd_serve_inflight"),
        "tokens_out": snapshot_value(snapshot, "hvd_serve_tokens_total")
        or 0,
        "decode_steps": snapshot_value(snapshot,
                                       "hvd_serve_decode_steps_total") or 0,
    }
    # serving fast path: block-paged KV cache + speculative decode health
    lookups = snapshot_value(snapshot, "hvd_serve_cache_lookups_total") or 0
    hits = snapshot_value(snapshot, "hvd_serve_cache_hits_total") or 0
    proposed = snapshot_value(snapshot,
                              "hvd_serve_spec_proposed_total") or 0
    accepted = snapshot_value(snapshot,
                              "hvd_serve_spec_accepted_total") or 0
    out["cache"] = {
        "pool_blocks": snapshot_value(snapshot,
                                      "hvd_serve_cache_pool_blocks"),
        "blocks_used": snapshot_value(snapshot,
                                      "hvd_serve_cache_blocks_used"),
        "shared_blocks": snapshot_value(snapshot,
                                        "hvd_serve_cache_shared_blocks"),
        "hit_pct": round(100.0 * hits / lookups, 1) if lookups else None,
        "reuse": snapshot_value(snapshot,
                                "hvd_serve_cache_reuse_total") or 0,
        "evictions": snapshot_value(snapshot,
                                    "hvd_serve_cache_evictions_total") or 0,
        "prefill_tokens_saved": snapshot_value(
            snapshot, "hvd_serve_cache_prefill_tokens_saved_total") or 0,
        "spec_accept_pct": round(100.0 * accepted / proposed, 1)
        if proposed else None,
    }
    out["batch_occupancy_mean"] = round(occ["sum"] / occ["count"], 3) \
        if occ else None
    for q, key in ((0.5, "latency_p50_ms"), (0.99, "latency_p99_ms")):
        v = histogram_quantile(lat, q) if lat else None
        out[key] = round(v * 1e3, 3) if v is not None else None
    return out


def _echo_trace(payload: dict, trace_id) -> dict:
    """Echo the trace id in EVERY response — 200s, 429s, 5xx — so a
    client can hand it back for correlation with the server-side spans."""
    if trace_id is not None:
        payload["trace_id"] = trace_id
    return payload


def tokenize(body: dict) -> list:
    """Token ids from a request body: ``tokens`` verbatim, else byte-level
    of ``prompt``."""
    if body.get("tokens") is not None:
        return [int(t) for t in body["tokens"]]
    return list(str(body.get("prompt", "")).encode())


class ServeFrontend:
    """Threaded ingress over a local batcher or a cluster router."""

    def __init__(self, batcher: Optional[ContinuousBatcher] = None,
                 router: Optional[RequestRouter] = None,
                 port: int = 0, addr: str = "0.0.0.0",
                 registry: Optional[MetricsRegistry] = None,
                 dispatch_timeout: float = 60.0,
                 admission: Optional[AdmissionController] = None):
        if (batcher is None) == (router is None):
            raise ValueError("pass exactly one of batcher= (local worker "
                             "mode) or router= (cluster ingress mode)")
        self.batcher = batcher
        self.router = router
        # SLO-aware admission (serve/admission.py): class shedding bites
        # in local mode (the queue lives here); quotas bite in both modes.
        self.admission = admission
        self.registry = registry if registry is not None else get_registry()
        self._dispatch_timeout = dispatch_timeout
        self._draining = threading.Event()
        frontend = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # silence
                pass

            def _reply(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                retry_after = payload.get("retry_after_seconds")
                if code == 429 and retry_after:
                    # integer ceiling: Retry-After is whole seconds
                    self.send_header("Retry-After",
                                     str(max(1, int(retry_after + 0.999))))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    if frontend.draining:
                        self._reply(503, {"status": "draining"})
                    else:
                        self._reply(200, {"status": "ok"})
                elif path == "/trace.json":
                    # this process's span buffer — what a collector (or
                    # the routed-mode ingress) fetches to merge a
                    # request's worker-side spans into one timeline
                    self._reply(200, {"spans": get_tracer().spans()})
                elif path == "/stats":
                    stats = serving_stats(frontend.registry.snapshot())
                    if frontend.batcher is not None and \
                            frontend.batcher.cache is not None:
                        # live conservation check (pool == free + charged
                        # + resident shared) — what the chaos drill
                        # asserts on the survivor after a peer kill
                        stats["cache"]["pool_balanced"] = \
                            frontend.batcher.cache.balanced()
                    if frontend.admission is not None:
                        stats["admission"] = frontend.admission.counters()
                    if frontend.router is not None:
                        # ingress mode: surface discovery health so load
                        # balancers/operators can see the router is
                        # serving from a stale (driver-outage) table
                        stats["router"] = frontend.router.stale_info()
                    self._reply(200, stats)
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                if path != "/v1/generate":
                    self._reply(404, {"error": "not found"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError) as e:
                    self._reply(400, {"error": f"bad request body: {e}"})
                    return
                code, payload = frontend.handle_generate(body)
                self._reply(code, payload)

        self._httpd = ThreadingHTTPServer((addr, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServeFrontend":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="hvd-serve-frontend")
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def set_draining(self, draining: bool = True):
        """Flip the health state a load balancer keys on: /healthz returns
        503 while the worker finishes what it already accepted."""
        if draining:
            self._draining.set()
        else:
            self._draining.clear()

    # -- request handling (transport-free, test-drivable) --------------------

    def handle_generate(self, body: dict):
        """(status_code, payload) for one generate request."""
        if self.batcher is not None:
            return self._handle_local(body)
        return self._handle_routed(body)

    def _admission_check(self, body: dict, queue_fill: float):
        """None when admitted, else the 429 (code, payload) pair."""
        if self.admission is None:
            return None
        verdict = self.admission.admit(body, queue_fill)
        if verdict.ok:
            return None
        tid = body.get("trace")
        journal.emit("serve", "shed", reason=verdict.reason,
                     priority_class=verdict.cls,
                     queue_fill=round(queue_fill, 3),
                     trace_id=tid.get("id") if isinstance(tid, dict)
                     else None)
        return 429, {"error": verdict.reason, "status": "rejected",
                     "priority_class": verdict.cls,
                     "retry_after_seconds": verdict.retry_after_seconds}

    def _handle_local(self, body: dict):
        tracer = get_tracer()
        # adopt the ingress sampling decision when routed to us; make it
        # here when WE are the ingress (trace id minted once per request)
        tid = tracer.adopt_or_start(body)
        if self.draining:
            return 503, _echo_trace(
                {"error": "worker draining", "status": "rejected"}, tid)
        with tracer.span(tid, ADMISSION, "frontend", mode="local"):
            shed = self._admission_check(
                body,
                self.batcher.pending() / max(self.batcher.queue_depth, 1))
            if shed is None:
                try:
                    req = self.batcher.submit(
                        tokenize(body),
                        max_new_tokens=body.get("max_new_tokens"),
                        deadline_ms=body.get("deadline_ms"),
                        request_id=body.get("id"),
                        trace=tid)
                except AdmissionRejected as e:
                    journal.emit("serve", "shed", reason=str(e),
                                 trace_id=tid)
                    shed = 429, {"error": str(e), "status": "rejected"}
        if shed is not None:
            code, payload = shed
            return code, _echo_trace(payload, tid)
        deadline_ms = body.get("deadline_ms")
        if deadline_ms is None:  # an explicit 0 means "already due",
            deadline_ms = self.batcher.default_deadline_ms  # not default
        if not req.wait(deadline_ms / 1e3 + _WAIT_SLACK_SEC):
            # the loop should have expired it long before this fires; a
            # hung executor must still not wedge the handler thread
            self.batcher.complete(req, "failed", "serving loop unresponsive")
            return 500, _echo_trace(req.result(), tid)
        code = {"ok": 200, "expired": 504, "failed": 500,
                "rejected": 429}.get(req.status, 500)
        return code, _echo_trace(req.result(), tid)

    def _handle_routed(self, body: dict):
        tracer = get_tracer()
        tid = tracer.adopt_or_start(body)
        rid = str(body.get("id") or id(body))
        # trace propagation: the worker adopts this id instead of making
        # its own sampling decision (one decision per request, at ingress)
        body = tracer.inject(dict(body, id=rid), tid)
        # ingress mode: the queue lives on the workers, so only quotas
        # bite here (fill 0.0); class shedding happens where the queue is
        with tracer.span(tid, ADMISSION, "frontend", mode="ingress"):
            shed = self._admission_check(body, 0.0)
        if shed is not None:
            code, payload = shed
            return code, _echo_trace(payload, tid)
        try:
            resp = self.router.submit(
                rid, body,
                lambda w, payload: post_json(
                    w.addr, w.port, "/v1/generate", payload,
                    timeout=self._dispatch_timeout))
        except NoWorkersError as e:
            return 503, _echo_trace(
                {"error": str(e), "status": "failed", "id": rid}, tid)
        code = {"ok": 200, "expired": 504, "failed": 500,
                "rejected": 429}.get(resp.get("status"), 200)
        return code, _echo_trace(resp, tid)


def main(argv=None) -> int:
    """``hvd-serve``: boot a demo local serving worker (tiny TP LM over
    every visible device, int8 activation collectives) and serve until
    interrupted. Production deployments embed :class:`ServeFrontend` /
    :mod:`horovod_tpu.serve.worker` instead."""
    import argparse
    from horovod_tpu.common.env_registry import env_int
    from horovod_tpu.serve.executor import ServingLoop, make_tp_lm_step

    parser = argparse.ArgumentParser(
        prog="hvd-serve", description="demo serving worker (tiny TP LM)")
    parser.add_argument("--port", type=int,
                        default=env_int("HOROVOD_SERVE_PORT", 0) or 0)
    parser.add_argument("--compression", default=None,
                        help="activation wire format: none | int8 "
                             "(default HOROVOD_SERVE_ACT_COMPRESSION)")
    args = parser.parse_args(argv)
    from horovod_tpu.common.env_registry import env_str
    compression = args.compression if args.compression is not None \
        else env_str("HOROVOD_SERVE_ACT_COMPRESSION")

    step_fn, info = make_tp_lm_step(compression=compression)
    batcher = ContinuousBatcher()
    loop = ServingLoop(step_fn, batcher).start()
    frontend = ServeFrontend(batcher=batcher, port=args.port).start()
    print(f"hvd-serve: listening on :{frontend.port} "
          f"(tp_world={info['tp_world']}, "
          f"compression={info['compression']})", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        loop.drain(timeout=10.0)
        loop.stop()
        frontend.stop()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
