"""Continuous-batching admission control and scheduling.

The serving-side counterpart of the engine's fusion buffer: where training
batches *tensors* to amortize collective launch cost, serving batches
*requests* to amortize forward-pass launch cost — but under a latency
budget, so the scheduler is deadline- and occupancy-driven rather than
byte-driven:

- **bounded admission queue**: ``HOROVOD_SERVE_QUEUE_DEPTH`` requests may
  wait; a full queue rejects immediately (backpressure — the caller gets a
  429-shaped error *now* instead of a timeout later, and offered load past
  saturation degrades gracefully instead of collapsing);
- **per-request deadlines**: every request carries an absolute deadline
  (client-supplied or ``HOROVOD_SERVE_DEADLINE_MS``); queued requests whose
  deadline passes are expired without ever costing a forward pass, and
  running ones are expired at the next step boundary;
- **length buckets shared with the flash-attention router**: a request is
  padded to the smallest power-of-two bucket that fits prompt + budget, and
  a batch only ever contains one bucket — so each bucket compiles exactly
  one executable for its whole lifetime, and the bucket's attention kernel
  route (XLA dot below ``HOROVOD_FLASH_MIN_SEQ``, flash at/above — the PR-2
  crossover) is a static property of the bucket, not a per-step surprise;
- **continuous (in-flight) batching**: finished requests free their slots
  at every decode-step boundary and queued same-bucket requests are
  admitted into them immediately — no drain-the-batch barrier.

All counters/histograms land in the process metrics registry
(``hvd_serve_*`` families), so the Prometheus exporter, ``hvd-top
--serving`` and the elastic driver see serving health with zero extra
plumbing.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from typing import List, Optional, Sequence, Tuple

from horovod_tpu.common import journal
from horovod_tpu.common.env_registry import env_float, env_int
from horovod_tpu.metrics.registry import MetricsRegistry, get_registry
from horovod_tpu.obs.tracing import QUEUE_WAIT, get_tracer, now_us

# Latency buckets for request-level histograms: serving targets live in the
# 1ms..10s decade.
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)
# Occupancy buckets (requests per step).
OCCUPANCY_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)

_TERMINAL = ("ok", "expired", "rejected", "failed")


class AdmissionRejected(RuntimeError):
    """The bounded admission queue is full (backpressure) or the request
    cannot fit any bucket; the caller should shed or retry elsewhere."""


def default_buckets(max_len: int = 2048, min_bucket: int = 32) -> Tuple[int,
                                                                        ...]:
    """Power-of-two padded lengths from ``min_bucket`` through ``max_len``
    — the same geometric ladder the flash-attention block sizes assume, so
    bucketed batches tile the kernel grid exactly."""
    out = []
    b = min_bucket
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


def bucket_for(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits ``length``; raises
    :class:`AdmissionRejected` when none does (the request could never
    complete within the configured context)."""
    for b in buckets:
        if length <= b:
            return b
    raise AdmissionRejected(
        f"request needs {length} tokens; largest bucket is {buckets[-1]}")


def bucket_plan(buckets: Optional[Sequence[int]] = None,
                max_len: int = 2048) -> List[dict]:
    """Static routing plan per bucket: which attention kernel the PR-2
    length router (:func:`horovod_tpu.ops.flash_attention.attention`) picks
    for sequences padded to that bucket. Because a batch is single-bucket,
    this is decided once per bucket — serving never flips kernels
    mid-request."""
    from horovod_tpu.ops.flash_attention import flash_min_seq
    crossover = flash_min_seq()
    return [{"bucket": b,
             "attention_kernel": "flash" if b >= crossover else "xla"}
            for b in (buckets or default_buckets(max_len))]


class InferenceRequest:
    """One admitted generation request.

    Completion is signalled through a per-request event; the HTTP frontend
    thread blocks on :meth:`wait` while the serving loop advances the
    request one token per step. Terminal states: ``ok`` (budget or EOS
    reached), ``expired`` (deadline passed — partial output is returned),
    ``failed`` (executor error).
    """

    __slots__ = ("id", "tokens", "max_new_tokens", "deadline", "arrival",
                 "bucket", "generated", "status", "error", "finished_at",
                 "lease", "trace", "_done")

    def __init__(self, tokens: Sequence[int], max_new_tokens: int,
                 deadline: float, bucket: int,
                 request_id: Optional[str] = None,
                 trace: Optional[str] = None):
        self.id = request_id or uuid.uuid4().hex[:16]
        self.tokens = [int(t) for t in tokens]
        self.max_new_tokens = int(max_new_tokens)
        self.deadline = float(deadline)  # absolute time.monotonic()
        self.arrival = time.monotonic()
        self.bucket = int(bucket)
        self.generated: List[int] = []
        self.lease = None  # CacheLease when the batcher owns a KV cache
        self.trace = trace  # sampled trace id (None on the untraced
        # fast path — every per-stage span emission keys on this)
        self.status = "queued"
        self.error = ""
        self.finished_at: Optional[float] = None
        self._done = threading.Event()

    @property
    def length(self) -> int:
        """Current true (unpadded) sequence length."""
        return len(self.tokens) + len(self.generated)

    @property
    def done(self) -> bool:
        return self.status in _TERMINAL

    def finish(self, status: str, error: str = ""):
        if self.done:
            return
        self.status = status
        self.error = error
        self.finished_at = time.monotonic()
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def result(self) -> dict:
        latency = (self.finished_at - self.arrival) \
            if self.finished_at is not None else None
        return {"id": self.id, "status": self.status,
                "tokens": list(self.generated),
                "error": self.error or None,
                "latency_ms": round(latency * 1e3, 3)
                if latency is not None else None}


class ContinuousBatcher:
    """Admission queue + slot scheduler for the serving loop.

    Thread contract: any number of producer threads call :meth:`submit`;
    exactly one consumer (the serving loop) calls :meth:`fill`,
    :meth:`observe_step` and :meth:`complete`.
    """

    def __init__(self, max_batch: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 max_len: int = 2048,
                 buckets: Optional[Sequence[int]] = None,
                 max_new_tokens_cap: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None,
                 cache=None):
        self.max_batch = max_batch if max_batch is not None \
            else env_int("HOROVOD_SERVE_MAX_BATCH")
        self.queue_depth = queue_depth if queue_depth is not None \
            else env_int("HOROVOD_SERVE_QUEUE_DEPTH")
        self.default_deadline_ms = default_deadline_ms \
            if default_deadline_ms is not None \
            else env_float("HOROVOD_SERVE_DEADLINE_MS")
        self.max_new_tokens_cap = max_new_tokens_cap \
            if max_new_tokens_cap is not None \
            else env_int("HOROVOD_SERVE_MAX_NEW_TOKENS")
        self.buckets = tuple(buckets) if buckets is not None \
            else default_buckets(max_len)
        # optional block-paged KV cache (serve/kv_cache.py): when set,
        # admission charges blocks against its bounded pool and the
        # expiry split below (release vs free) keeps it balanced
        self.cache = cache
        self._queue: deque = deque()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        reg = registry if registry is not None else get_registry()
        self._requests = {s: reg.counter("hvd_serve_requests_total",
                                         status=s)
                          for s in _TERMINAL}
        self._admitted = reg.counter("hvd_serve_admitted_total")
        self._tokens_out = reg.counter("hvd_serve_tokens_total")
        self._depth = reg.gauge("hvd_serve_queue_depth")
        self._occupancy = reg.histogram("hvd_serve_batch_occupancy",
                                        buckets=OCCUPANCY_BUCKETS)
        self._latency = reg.histogram("hvd_serve_request_latency_seconds",
                                      buckets=LATENCY_BUCKETS)
        self._queue_wait = reg.histogram("hvd_serve_queue_wait_seconds",
                                         buckets=LATENCY_BUCKETS)

    # -- producer side -------------------------------------------------------

    def submit(self, tokens: Sequence[int],
               max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               request_id: Optional[str] = None,
               trace: Optional[str] = None) -> InferenceRequest:
        """Admit a request or raise :class:`AdmissionRejected`.

        Rejections are counted and *immediate* — backpressure is the
        defined behavior past saturation, never an unbounded queue. The
        bucket is fixed here (prompt + token budget), so a request's batch
        shape and kernel route never change mid-flight."""
        budget = min(int(max_new_tokens) if max_new_tokens is not None
                     else self.max_new_tokens_cap, self.max_new_tokens_cap)
        budget = max(budget, 1)
        try:
            bucket = bucket_for(len(tokens) + budget, self.buckets)
        except AdmissionRejected:
            self._requests["rejected"].inc()
            raise
        ddl_ms = float(deadline_ms) if deadline_ms is not None \
            else self.default_deadline_ms
        req = InferenceRequest(tokens, budget,
                               time.monotonic() + ddl_ms / 1e3, bucket,
                               request_id=request_id, trace=trace)
        with self._lock:
            if len(self._queue) >= self.queue_depth:
                self._requests["rejected"].inc()
                req.finish("rejected", "admission queue full (backpressure)")
                raise AdmissionRejected(
                    f"admission queue full ({self.queue_depth} waiting)")
            if self.cache is not None:
                from horovod_tpu.serve.kv_cache import CacheExhausted
                try:
                    # charge the block pool NOW: a request that cannot
                    # get cache blocks is a 429 at admission, never an
                    # OOM mid-decode
                    req.lease = self.cache.admit(req.tokens, budget,
                                                 trace=trace)
                except CacheExhausted as e:
                    self._requests["rejected"].inc()
                    req.finish("rejected", str(e))
                    raise AdmissionRejected(str(e)) from None
            self._queue.append(req)
            self._depth.set(len(self._queue))
            self._admitted.inc()
            self._work.notify()
        return req

    def pending(self) -> int:
        with self._lock:
            return len(self._queue)

    # -- consumer (serving loop) side ----------------------------------------

    def wait_for_work(self, timeout: float) -> bool:
        """Block until something is queued (or timeout); True when work
        exists."""
        with self._lock:
            if self._queue:
                return True
            self._work.wait(timeout)
            return bool(self._queue)

    def _expire_queued_locked(self, now: float):
        kept: deque = deque()
        while self._queue:
            req = self._queue.popleft()
            if req.deadline <= now:
                self._finish(req, "expired",
                             "deadline passed while queued")
            else:
                kept.append(req)
        self._queue = kept

    def fill(self, running: List[InferenceRequest]) -> List[InferenceRequest]:
        """One scheduling pass: expire stale queued requests, then admit
        queued requests into free slots. Single-bucket batches: the first
        admitted request pins the bucket; only same-bucket requests join
        (others keep their arrival order for the next batch — skipped, not
        reordered past each other)."""
        now = time.monotonic()
        out = [r for r in running if not r.done]
        with self._lock:
            self._expire_queued_locked(now)
            bucket = out[0].bucket if out else None
            if self._queue and bucket is None:
                bucket = self._queue[0].bucket
            skipped: List[InferenceRequest] = []
            while self._queue and len(out) < self.max_batch:
                req = self._queue.popleft()
                if req.bucket != bucket:
                    skipped.append(req)
                    continue
                req.status = "running"
                wait = now - req.arrival
                self._queue_wait.observe(wait)
                if req.trace is not None:
                    # span start back-dated to arrival: the wait is over
                    # by the time anyone can observe it
                    get_tracer().record(
                        req.trace, QUEUE_WAIT, "batcher",
                        now_us() - wait * 1e6, wait * 1e6,
                        bucket=req.bucket)
                out.append(req)
            for req in reversed(skipped):
                self._queue.appendleft(req)
            self._depth.set(len(self._queue))
        return out

    def observe_step(self, occupancy: int):
        if occupancy > 0:
            self._occupancy.observe(occupancy)

    def complete(self, req: InferenceRequest, status: str = "ok",
                 error: str = ""):
        self._finish(req, status, error)

    def _finish(self, req: InferenceRequest, status: str, error: str = ""):
        if req.done:
            return
        was_queued = req.status == "queued"
        if status in ("expired", "rejected"):
            journal.emit("serve", f"request_{status}", trace_id=req.trace,
                         request_id=req.id, error=error,
                         was_queued=was_queued)
        req.finish(status, error)
        if req.lease is not None and self.cache is not None:
            # the expiry split: a request that never left the queue only
            # ever held charged capacity (release — it provably never
            # bound a block); one that ran frees exactly what it charged
            # at the step boundary where its (partial) output returns
            if was_queued:
                self.cache.release(req.lease)
            else:
                self.cache.free(req.lease)
        self._requests[status].inc()
        if status == "ok":
            self._tokens_out.inc(len(req.generated))
        self._latency.observe(req.finished_at - req.arrival)
