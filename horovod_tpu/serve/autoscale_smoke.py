"""Closed-loop autoscale smoke: load trace in, fleet-size trace out.

The BENCH ``autoscale`` block and ``make autoscale-smoke`` both run this:
an in-process serving fleet (one ContinuousBatcher + ServingLoop + token
bucket/priority admission per worker, fronted by the real RequestRouter)
driven by the REAL :class:`~horovod_tpu.runner.elastic.autoscaler.Autoscaler`
— the same policy object, KV decision records (a live in-memory KVServer,
epoch-claimed writes) and decide→drain→resize→ack machine the elastic
driver runs. Only the actuation surface differs: ``scale_up`` spawns an
in-process worker after a short simulated provisioning delay, and
``start_drain`` marks the victim draining in the router table *immediately*
(the PR-15 announce satellite), lets it finish everything accepted, then
removes it.

Two canned traces:

- ``flash`` — steady base load, a flash crowd several times one worker's
  capacity, then recession: the loop must scale up under the crowd, hold
  p99 inside the SLO bound, and drain back down afterwards. With
  ``chaos_kill`` a worker is SIGKILL-equivalently dropped *while the
  scale-up resize is in flight*; the router re-routes its in-flight
  requests (no-silent-loss) and the fleet still converges.
- ``diurnal`` — a rise-and-fall staircase (the day curve compressed to
  seconds): the fleet should follow it up and back down without flapping.

Acceptance, computed over the run and printed as JSON:
**accepted-request loss == 0** (no failed requests, router lost counter
pinned at zero — 429s/sheds are backpressure, not loss), **p99 within the
SLO bound** in every completed-load window, **a scale-up AND a
drain-based scale-down** in the decision log, and **no flapping** (no
opposite-direction decisions closer than one hysteresis window).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Dict, List, Optional

from horovod_tpu.metrics.registry import MetricsRegistry
from horovod_tpu.runner.elastic.autoscaler import (Autoscaler,
                                                   AutoscalePolicy,
                                                   WorkerSLO,
                                                   worker_slo_from_snapshot)
from horovod_tpu.serve.admission import AdmissionController
from horovod_tpu.serve.batcher import AdmissionRejected, ContinuousBatcher
from horovod_tpu.serve.executor import ServingLoop, make_toy_step
from horovod_tpu.serve.loadgen import run_load
from horovod_tpu.serve.router import NoWorkersError, RequestRouter


class FleetWorker:
    """One in-process serving worker: batcher + decode loop + admission,
    with a dict-in/dict-out ``handle`` standing in for the HTTP frontend
    (same verdicts, no sockets)."""

    def __init__(self, wid: str, service_ms: float, max_batch: int,
                 queue_depth: int, deadline_ms: float,
                 max_new_tokens: int):
        self.id = wid
        self.registry = MetricsRegistry()
        self.batcher = ContinuousBatcher(
            max_batch=max_batch, queue_depth=queue_depth,
            default_deadline_ms=deadline_ms, max_len=64,
            max_new_tokens_cap=max_new_tokens, registry=self.registry)
        base_step = make_toy_step()
        delay = service_ms / 1e3

        def step(tokens, lengths):
            time.sleep(delay)  # the simulated forward-pass cost
            return base_step(tokens, lengths)

        self.loop = ServingLoop(step, self.batcher,
                                registry=self.registry).start()
        self.admission = AdmissionController(registry=self.registry)
        self.killed = threading.Event()
        self._deadline_s = deadline_ms / 1e3

    def handle(self, payload: dict) -> dict:
        """The frontend contract; raises (transport-style) when killed so
        the router's no-silent-loss retry re-routes."""
        if self.killed.is_set():
            raise ConnectionError(f"worker {self.id} is dead")
        verdict = self.admission.admit(
            payload, self.batcher.pending() /
            max(self.batcher.queue_depth, 1))
        if not verdict.ok:
            return {"status": "rejected", "error": verdict.reason,
                    "retry_after_seconds": verdict.retry_after_seconds}
        try:
            req = self.batcher.submit(
                payload.get("tokens", [1, 2, 3]),
                max_new_tokens=payload.get("max_new_tokens"),
                request_id=payload.get("id"))
        except AdmissionRejected as e:
            return {"status": "rejected", "error": str(e)}
        deadline = time.monotonic() + self._deadline_s + 5.0
        while not req.wait(0.05):
            if self.killed.is_set():
                raise ConnectionError(
                    f"worker {self.id} died with request in flight")
            if time.monotonic() > deadline:
                self.batcher.complete(req, "failed", "worker wedged")
                break
        if self.killed.is_set():
            raise ConnectionError(f"worker {self.id} died at completion")
        return req.result()

    def slo(self) -> WorkerSLO:
        slo = worker_slo_from_snapshot(self.id, self.registry.snapshot())
        return slo if slo is not None else WorkerSLO(self.id, 0.0, None,
                                                    None, 0.0)

    def kill(self):
        """The chaos leg: everything in flight raises back to the router
        (which re-routes it), nothing is silently dropped."""
        self.killed.set()
        self.loop.stop()

    def stop(self):
        self.loop.stop()


class SimFleet:
    """The Autoscaler's ``fleet_ops`` over in-process workers + a real
    RequestRouter (immediate-drain announce included)."""

    def __init__(self, service_ms: float = 40.0, max_batch: int = 2,
                 queue_depth: int = 16, deadline_ms: float = 8000.0,
                 max_new_tokens: int = 4, spawn_delay: float = 0.3):
        self.registry = MetricsRegistry()
        self.router = RequestRouter(retry_limit=3, registry=self.registry)
        self.workers: Dict[str, FleetWorker] = {}
        self.draining: set = set()
        self._cfg = dict(service_ms=service_ms, max_batch=max_batch,
                         queue_depth=queue_depth, deadline_ms=deadline_ms,
                         max_new_tokens=max_new_tokens)
        self.spawn_delay = spawn_delay
        self.generation = 0
        self._n = 0
        self._lock = threading.Lock()
        self._spawn_threads: List[threading.Thread] = []

    # -- router table ---------------------------------------------------------

    def _publish(self):
        with self._lock:
            self.generation += 1
            entries = []
            for wid, w in self.workers.items():
                if w.killed.is_set():
                    continue
                e = {"id": wid, "addr": "local", "port": 0,
                     "generation": self.generation}
                if wid in self.draining:
                    e["draining"] = True
                entries.append(e)
            gen = self.generation
        self.router.update_workers(entries, gen)

    def _add_worker(self):
        with self._lock:
            wid = f"w{self._n}"
            self._n += 1
            self.workers[wid] = FleetWorker(wid, **self._cfg)
        self._publish()

    # -- fleet_ops (the Autoscaler drives these) ------------------------------

    def scale_up(self):
        def spawn():
            time.sleep(self.spawn_delay)  # simulated provisioning
            self._add_worker()

        t = threading.Thread(target=spawn, daemon=True)
        t.start()
        self._spawn_threads.append(t)

    def start_drain(self, victim: str):
        with self._lock:
            if victim not in self.workers or victim in self.draining:
                return
            self.draining.add(victim)
        self._publish()  # the announce: no new placements from here on

        def drain():
            w = self.workers.get(victim)
            if w is not None:
                w.loop.drain(timeout=30.0)
                w.stop()
            with self._lock:
                self.workers.pop(victim, None)
                self.draining.discard(victim)
            self._publish()

        threading.Thread(target=drain, daemon=True).start()

    # -- chaos / observation --------------------------------------------------

    def kill(self, wid: str) -> bool:
        with self._lock:
            w = self.workers.get(wid)
            if w is None or wid in self.draining:
                return False
        w.kill()
        self._publish()
        return True

    def accepting_ids(self) -> List[str]:
        with self._lock:
            return [wid for wid, w in self.workers.items()
                    if wid not in self.draining and not w.killed.is_set()]

    def fleet_slos(self) -> List[WorkerSLO]:
        with self._lock:
            live = [(wid, w) for wid, w in self.workers.items()
                    if wid not in self.draining and not w.killed.is_set()]
        return [w.slo() for _wid, w in live]

    def draining_keys(self) -> List[str]:
        with self._lock:
            return list(self.draining)

    def submit(self, payload: dict) -> dict:
        rid = str(payload.get("id") or id(payload))
        payload = dict(payload, id=rid)
        try:
            return self.router.submit(
                rid, payload,
                lambda w, p: self.workers[w.id].handle(p))
        except NoWorkersError:
            return {"status": "failed", "error": "no accepting worker"}

    def lost_requests(self) -> float:
        from horovod_tpu.metrics import snapshot_value
        return snapshot_value(self.registry.snapshot(),
                              "hvd_serve_lost_total") or 0.0

    def close(self):
        for t in self._spawn_threads:
            t.join(timeout=5.0)
        with self._lock:
            workers = list(self.workers.values())
        for w in workers:
            w.stop()


TRACES = {
    # (offered_qps_multiplier_of_capacity, seconds_multiplier) phases;
    # capacity here is ONE worker's measured ceiling
    "flash": [(0.4, 1.0), (2.4, 2.0), (0.15, 2.5)],
    "diurnal": [(0.3, 1.0), (0.8, 1.0), (1.6, 1.5), (0.8, 1.0),
                (0.08, 2.5)],
}


def run_smoke(trace: str = "flash", chaos_kill: bool = False,
              seconds_scale: float = 3.0, service_ms: float = 40.0,
              max_batch: int = 2, max_new_tokens: int = 4,
              p99_bound_ms: float = 2500.0, queue_bound: int = 4,
              max_workers: int = 4, interval: float = 0.25,
              kv_dir: Optional[str] = None) -> dict:
    """One closed loop: trace → fleet resize decisions → acceptance
    flags. ``seconds_scale`` stretches every phase (CI uses small values;
    the Makefile default gives the policy room to breathe)."""
    from horovod_tpu.runner.http_kv import KVServer

    fleet = SimFleet(service_ms=service_ms, max_batch=max_batch,
                     max_new_tokens=max_new_tokens)
    fleet._add_worker()
    # one worker's theoretical ceiling: max_batch concurrent requests,
    # each costing max_new_tokens decode steps of service_ms
    capacity = max_batch / (max_new_tokens * service_ms / 1e3)
    policy = AutoscalePolicy(
        min_workers=1, max_workers=max_workers,
        queue_bound=float(queue_bound), p99_bound_ms=p99_bound_ms,
        idle_occupancy=0.25, up_windows=2, down_windows=4,
        up_cooldown=2 * interval, down_cooldown=8 * interval)
    kv = KVServer(port=0, kv_dir=kv_dir).start()
    scaler = Autoscaler(fleet, kv=kv, epoch=kv.epoch, policy=policy,
                        registry=fleet.registry)

    stop = threading.Event()
    fleet_trace: List[dict] = []
    t0 = time.monotonic()

    def tick_loop():
        while not stop.is_set():
            try:
                scaler.tick(fleet.fleet_slos(), fleet.draining_keys())
            except Exception as e:  # noqa: BLE001 — record, keep looping
                fleet_trace.append({"t": round(time.monotonic() - t0, 2),
                                    "error": repr(e)})
            fleet_trace.append({
                "t": round(time.monotonic() - t0, 2),
                "fleet": len(fleet.accepting_ids()),
                "draining": len(fleet.draining_keys()),
            })
            stop.wait(interval)

    ticker = threading.Thread(target=tick_loop, daemon=True)
    ticker.start()

    chaos = {"requested": chaos_kill, "killed": None}
    if chaos_kill:
        def chaos_loop():
            # SIGKILL-equivalent drop of the ORIGINAL worker the moment
            # the scale-up's spawn lands (the resize window): its
            # in-flight requests re-route to the joiner, the continued
            # pressure re-grows the fleet
            saw_up = False
            while not stop.is_set():
                pending = scaler.pending
                if pending and pending.get("action") == "up":
                    saw_up = True
                if saw_up and len(fleet.accepting_ids()) >= 2:
                    victim = sorted(fleet.accepting_ids())[0]
                    fleet.kill(victim)
                    chaos["killed"] = victim
                    chaos["at_state"] = (pending or {}).get("state",
                                                            "acked")
                    chaos["t"] = round(time.monotonic() - t0, 2)
                    return
                time.sleep(0.02)

        threading.Thread(target=chaos_loop, daemon=True).start()

    def make_payload(i):
        return {"tokens": [(i * 7 + j) % 61 for j in range(8)],
                "max_new_tokens": max_new_tokens,
                "priority": ("batch", "standard", "premium")[i % 3]}

    windows = []
    try:
        for mult, dur in TRACES[trace]:
            qps = max(1.0, round(capacity * mult, 1))
            win = run_load(fleet.submit, qps, dur * seconds_scale,
                           make_payload)
            win["fleet_at_end"] = len(fleet.accepting_ids())
            windows.append(win)
    finally:
        # let in-flight drains/spawns settle before judging the run
        deadline = time.monotonic() + 10.0
        while (fleet.draining_keys() or
               (scaler.pending is not None)) and \
                time.monotonic() < deadline:
            time.sleep(0.1)
        stop.set()
        ticker.join(timeout=5.0)
        fleet.close()
        kv.stop()

    decisions = [{k: d.get(k) for k in ("seq", "action", "victim",
                                        "reason", "state", "outcome",
                                        "ts")}
                 for d in scaler.decisions]
    # flapping check: opposite-direction decisions closer together than
    # one hysteresis window are exactly what the hysteresis must prevent
    hysteresis_s = policy.down_windows * interval
    flap = False
    for a, b in zip(scaler.decisions, scaler.decisions[1:]):
        if a["action"] != b["action"] and \
                b["ts"] - a["ts"] < hysteresis_s:
            flap = True
    from horovod_tpu.metrics import snapshot_value
    rerouted = snapshot_value(fleet.registry.snapshot(),
                              "hvd_serve_rerouted_total") or 0.0
    loss = sum(w["failed"] for w in windows) + fleet.lost_requests()
    p99s = [w["p99_ms"] for w in windows if w["p99_ms"] is not None]
    fleet_sizes = [p["fleet"] for p in fleet_trace if "fleet" in p]
    return {
        "trace": trace,
        "single_worker_capacity_qps": round(capacity, 1),
        "p99_bound_ms": p99_bound_ms,
        "windows": windows,
        "decisions": decisions,
        "fleet_trace": fleet_trace,
        "fleet_max": max(fleet_sizes) if fleet_sizes else 0,
        "fleet_final": fleet_sizes[-1] if fleet_sizes else 0,
        "chaos": chaos,
        "scale_up_seen": any(d["action"] == "up" for d in decisions),
        "scale_down_seen": any(d["action"] == "down" for d in decisions),
        "max_p99_ms": max(p99s) if p99s else None,
        "p99_within_bound": bool(p99s) and max(p99s) <= p99_bound_ms,
        "accepted_loss": loss,
        "no_flap": not flap,
        "rerouted": rerouted,
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvd-autoscale-smoke",
        description="bounded closed-loop autoscale demo: loadgen flash "
                    "crowd -> scale-up -> recede -> drain-based "
                    "scale-down, with an optional chaos kill mid-resize")
    parser.add_argument("--trace", choices=sorted(TRACES), default="flash")
    parser.add_argument("--chaos-kill", action="store_true")
    parser.add_argument("--seconds-scale", type=float, default=3.0)
    args = parser.parse_args(argv)
    result = run_smoke(trace=args.trace, chaos_kill=args.chaos_kill,
                       seconds_scale=args.seconds_scale)
    print(json.dumps(result, indent=2))
    ok = (result["accepted_loss"] == 0 and result["no_flap"] and
          result["scale_up_seen"] and result["scale_down_seen"] and
          result["p99_within_bound"])
    if args.chaos_kill:
        # the chaos leg must actually have run: a kill landed and its
        # in-flight requests were re-routed (not merely not-lost)
        ok = ok and result["chaos"]["killed"] is not None and \
            result["rerouted"] > 0
    if not ok:
        print("autoscale smoke FAILED acceptance", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
