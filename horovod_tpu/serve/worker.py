"""Per-process serving worker (``python -m horovod_tpu.serve.worker``).

What the elastic driver spawns for the serving plane. Each worker:

1. rendezvouses through the standard elastic handshake (READY/go barrier,
   :mod:`horovod_tpu.runner.elastic.worker`) when driver-spawned;
2. boots a local continuous-batching serving stack (batcher → serving loop
   → HTTP frontend) and **registers its endpoint** in the rendezvous KV
   under ``serve_addr/<host>/<local_rank>`` — the driver aggregates these
   into ``serve_targets`` each heartbeat, which is what the ingress
   router's :meth:`~horovod_tpu.serve.router.RequestRouter.refresh_from_kv`
   consumes;
3. when the job has peers and a controller, opens an engine session and
   exchanges a small **heartbeat allreduce** between decode steps — real
   serving-regime traffic: sub-4-KiB, latency-bound, riding the
   serving-mode express lane and recorded by the flight recorder like any
   other collective. A peer death therefore surfaces as a fast-abort
   within one cycle, not a 30 s timeout;
4. on a generation change (driver notify key, or an engine abort after a
   peer death) it **drains instead of dropping**: /healthz flips to 503,
   accepted requests finish, then the worker re-rendezvouses and
   re-registers under the new generation — or exits cleanly if its slot
   was removed;
5. exits 0 when the KV publishes ``serve_stop`` (job teardown).

The default model is the numpy toy step (instant startup — what the
subprocess fault tests spawn); ``--model tp`` boots the tensor-parallel LM
with int8 activation collectives instead.
"""

from __future__ import annotations

import argparse
import socket
import sys
import time
from typing import Optional

import numpy as np

from horovod_tpu.common import kv_keys
from horovod_tpu.common.env_registry import (env_bool, env_int, env_is_set,
                                             env_str)
from horovod_tpu.common.exceptions import HorovodInternalError
from horovod_tpu.common.hvd_logging import get_logger
from horovod_tpu.serve.batcher import ContinuousBatcher
from horovod_tpu.serve.executor import ServingLoop, make_toy_step
from horovod_tpu.serve.frontend import ServeFrontend

HB_INTERVAL_SEC = 0.25
POLL_INTERVAL_SEC = 0.05
# serve_stop / resize notifications are HTTP round trips against the one
# rendezvous KV server every worker shares — poll them at heartbeat-ish
# cadence, not the loop tick (40 req/s/worker of pure polling otherwise)
KV_POLL_INTERVAL_SEC = 1.0


class EngineHeartbeat:
    """Small-tensor liveness collective between serving peers.

    One 16-element fp32 allreduce (64 bytes — deep inside the low-latency
    threshold) per interval, named per generation so every rank of a
    generation advances the same sequence. Failure means a peer died or
    aborted: the caller tears the session down and re-rendezvouses."""

    def __init__(self, rank: int, size: int, generation: int):
        from horovod_tpu.engine import bindings
        self._bindings = bindings
        self._lib = bindings.load_library()
        self.session = bindings.EngineSession(
            rank=rank, size=size, transport="tcp",
            local_rank=env_int("HOROVOD_LOCAL_RANK"),
            local_size=env_int("HOROVOD_LOCAL_SIZE"))
        self._gen = generation
        self._seq = 0
        session = self.session

        def cb(resp):
            buf = np.ones(16, np.float32)
            return self._lib.hvdtpu_data_allreduce(
                session._session, buf.ctypes.data, 16,
                bindings.DTYPE_IDS["float32"], 0, 1.0, 1.0)

        self.session.set_execute_callback(cb)

    def beat(self, timeout: float = 30.0):
        """One heartbeat collective; raises HorovodInternalError on peer
        failure (fast abort)."""
        from horovod_tpu.engine.bindings import OP_ALLREDUCE
        name = f"serve.hb.g{self._gen}.{self._seq}"
        self._seq += 1
        h = self.session.enqueue(name, OP_ALLREDUCE, "float32", [16])
        self.session.wait(h, timeout=timeout)

    def close(self):
        try:
            self.session.shutdown()
        except Exception:  # noqa: BLE001 — already aborted/dead is fine
            try:
                self.session.destroy()
            except Exception:  # noqa: BLE001
                pass


class ServeWorker:
    """Local serving stack + KV registration for one process."""

    def __init__(self, step_fn=None, port: Optional[int] = None,
                 batcher: Optional[ContinuousBatcher] = None,
                 admission=None):
        from horovod_tpu.serve.admission import controller_from_env
        if batcher is None:
            # the serving fast path: a block-paged KV cache owned by the
            # batcher (admission charges its bounded pool) and, when
            # HOROVOD_SERVE_SPEC_DECODE is on, draft-model speculative
            # decoding over the cached toy model
            from horovod_tpu.serve.kv_cache import PagedKVCache
            batcher = ContinuousBatcher(cache=PagedKVCache())
        self.batcher = batcher
        cached = draft = None
        if step_fn is None and self.batcher.cache is not None:
            from horovod_tpu.serve.executor import make_toy_cached_step
            cached = make_toy_cached_step()
            if env_bool("HOROVOD_SERVE_SPEC_DECODE"):
                draft = make_toy_cached_step()
        self.loop = ServingLoop(step_fn or make_toy_step(), self.batcher,
                                cached_step=cached, draft_step=draft)
        # SLO-aware admission: priority-class shedding + tenant quotas
        # (env-configured; the defaults are backwards-compatible — an
        # unprioritized request is only ever shed by the full queue)
        self.admission = admission if admission is not None \
            else controller_from_env()
        self.frontend = ServeFrontend(
            batcher=self.batcher,
            admission=self.admission,
            port=port if port is not None
            else (env_int("HOROVOD_SERVE_PORT") or 0))
        self._log = get_logger("serve.worker")
        self._kv = None

    def start(self) -> "ServeWorker":
        self.loop.start()
        self.frontend.start()
        return self

    def stop(self):
        self.loop.stop()
        self.frontend.stop()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Flip health to draining and finish everything accepted."""
        self.frontend.set_draining(True)
        drained = self.loop.drain(timeout)
        if not drained:
            self._log.warning("drain timed out with work still in flight")
        return drained

    # -- KV registration -----------------------------------------------------

    def _slot(self):
        return (env_str("HOROVOD_HOSTNAME", socket.gethostname()),
                str(env_int("HOROVOD_LOCAL_RANK")))

    def register(self, kv_client, generation: int):
        """Publish this worker's endpoint for the driver's serve_targets
        aggregation (exporter._publish_endpoint pattern)."""
        self._kv = kv_client
        host, local_rank = self._slot()
        addr = "127.0.0.1" if host == "localhost" else host
        kv_client.put_json(
            kv_keys.serve_addr(host, local_rank),
            {"id": f"{host}/{local_rank}", "addr": addr,
             "port": self.frontend.port, "rank": env_int("HOROVOD_RANK"),
             "generation": generation}, timeout=5.0)
        self._log.info("registered serve endpoint :%d (generation %d)",
                       self.frontend.port, generation)

    def deregister(self):
        if self._kv is None:
            return
        host, local_rank = self._slot()
        try:
            self._kv.delete(kv_keys.serve_addr(host, local_rank))
        except Exception:  # noqa: BLE001 — KV may already be gone at exit
            pass


def _build_step(model: str, compression: Optional[str]):
    if model == "tp":
        from horovod_tpu.serve.executor import make_tp_lm_step
        step_fn, info = make_tp_lm_step(
            compression=compression
            if compression is not None
            else env_str("HOROVOD_SERVE_ACT_COMPRESSION"))
        return step_fn
    # None -> ServeWorker's default stack: the cached toy model behind
    # the block-paged KV cache (+ speculative decode when enabled)
    return None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="hvd-serve-worker")
    parser.add_argument("--model", choices=("toy", "tp"), default="toy")
    parser.add_argument("--compression", default=None)
    parser.add_argument("--port", type=int, default=None)
    args = parser.parse_args(argv)

    log = get_logger("serve.worker")
    from horovod_tpu.runner.elastic import preempt
    from horovod_tpu.runner.elastic import worker as elastic_worker

    # Preemption notices (SIGTERM by default) drain a serve worker the
    # same way they drain a training worker: announce on the KV, finish
    # everything accepted, record DRAINED, exit 0 — this is also how the
    # autoscaler's scale-down reaches us (drain, never a kill).
    preempt.install_preempt_handler()

    elastic = elastic_worker.is_elastic_worker()
    generation = 0
    if elastic:
        generation = elastic_worker.rendezvous()
        # driver-recovery adoption + headless outage accounting
        elastic_worker.start_heartbeat()
    kv = elastic_worker.kv_client() \
        if env_is_set("HOROVOD_RENDEZVOUS_ADDR") else None

    worker = ServeWorker(_build_step(args.model, args.compression),
                         port=args.port).start()
    if kv is not None:
        worker.register(kv, generation)

    def make_heartbeat() -> Optional[EngineHeartbeat]:
        size = env_int("HOROVOD_SIZE")
        if size <= 1 or not env_int("HOROVOD_CONTROLLER_PORT"):
            return None
        return EngineHeartbeat(env_int("HOROVOD_RANK"), size, generation)

    hb = make_heartbeat()
    last_beat = 0.0
    last_kv_poll = 0.0
    try:
        while True:
            time.sleep(POLL_INTERVAL_SEC)
            now = time.monotonic()
            if preempt.preempt_requested():
                # the handler already announced the drain on the KV; we
                # finish what we accepted, then depart cleanly
                log.info("preemption notice: draining and exiting")
                worker.drain(timeout=30.0)
                worker.deregister()
                if elastic:
                    try:
                        elastic_worker.record_state(
                            generation, elastic_worker.DRAINED, kv)
                    except Exception:  # noqa: BLE001 — exit 0 still says
                        pass  # clean
                return 0
            kv_due = kv is not None and \
                now - last_kv_poll >= KV_POLL_INTERVAL_SEC
            if kv_due:
                last_kv_poll = now
                if kv.get_json(kv_keys.serve_stop(), timeout=1.0) is not None:
                    log.info("serve_stop published; draining and exiting")
                    worker.drain(timeout=30.0)
                    if elastic:
                        elastic_worker.record_state(
                            generation, elastic_worker.SUCCESS, kv)
                    return 0
            reset_needed = False
            heartbeat_failed = False
            if hb is not None and now - last_beat >= HB_INTERVAL_SEC:
                last_beat = now
                try:
                    hb.beat()
                except HorovodInternalError as e:
                    # peer death/abort: fast abort delivered this within
                    # one cycle. Keep serving what we accepted; rejoin the
                    # next generation (elastic) or exit loudly (static).
                    log.warning("heartbeat collective failed (%s)", e)
                    reset_needed = heartbeat_failed = True
            if elastic and not reset_needed and kv_due:
                new_gen = elastic_worker.poll_notification(kv)
                reset_needed = new_gen is not None
            if reset_needed:
                if hb is not None:
                    hb.close()
                    hb = None
                if not elastic:
                    # no rendezvous to rejoin: a static job cannot heal —
                    # finish what we accepted, then fail loudly so the
                    # launcher sees a dead worker instead of a silent
                    # heartbeat-retry spin
                    log.error("peer failure in a static job; draining "
                              "and exiting")
                    worker.drain(timeout=30.0)
                    worker.deregister()
                    return 1
                if heartbeat_failed:
                    elastic_worker.request_new_generation()
                try:
                    generation = elastic_worker.rendezvous()
                except SystemExit:
                    # this slot was removed: drain instead of dropping
                    log.info("slot removed at resize; draining")
                    worker.drain(timeout=30.0)
                    worker.deregister()
                    return 0
                worker.register(kv, generation)
                hb = make_heartbeat()
    finally:
        worker.stop()


if __name__ == "__main__":
    sys.exit(main())
