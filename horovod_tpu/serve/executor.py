"""Decode loop + tensor-parallel inference execution.

The serving loop is a single consumer thread over a
:class:`~horovod_tpu.serve.batcher.ContinuousBatcher`: every iteration it
(1) frees finished slots and admits queued same-bucket requests into them,
(2) runs ONE decode step for the whole in-flight batch, (3) appends each
request's next token and completes any that reached their budget, EOS, or
deadline. Continuous batching falls out of doing admission at every step
boundary rather than per batch.

``step_fn`` is the execution contract::

    step_fn(tokens [B, L] int32, lengths [B] int32) -> next_token [B] int

with ``B`` fixed at ``max_batch`` (inactive rows padded) and ``L`` the
batch's bucket — so each bucket compiles exactly one executable.

Two built-in step functions:

- :func:`make_toy_step` — deterministic numpy-only model for tests and
  subprocess serve workers (no jax import, instant startup);
- :func:`make_tp_lm_step` — a tensor-parallel decoder over the ``model``
  mesh axis whose per-layer row-parallel reduction rides the EQuARX int8
  quantized allreduce when ``compression="int8"``
  (:func:`horovod_tpu.parallel.tp.tp_mlp_inference`). This is the int8
  *activation* path the ROADMAP calls out: PR 1 built the quantized
  collectives for gradients; serving is where they meet activations.

Decode on the plain ``step_fn`` path is prefill-style recompute (the full
forward re-runs per token over the padded bucket): shapes stay static and
the executor stays tiny.

**The serving fast path** (``docs/DESIGN.md`` "Serving fast path") layers
two optimizations on top, both behind the :class:`CachedStep` contract::

    cached.advance(tokens [B, L] int32, upto [B] int32,
                   state [B, H] f32, state_len [B] int32)
        -> (preds [B, A] int32, states [B, A, H] f32)

which consumes positions ``state_len..upto-1`` per row and returns the
greedy prediction + model-state checkpoint after each consumed position.
With it the loop:

- **pages model state through the block-paged KV cache**
  (:mod:`horovod_tpu.serve.kv_cache`): per-step cost drops from O(L) to
  O(new tokens), prefill resumes from shared-prefix block checkpoints
  (hash hits pay zero prefill), and each request's block table is bound /
  freed at step boundaries so the pool accounting the
  ``PagedCacheSpec`` model-checks holds live;
- **speculative decoding** (``HOROVOD_SERVE_SPEC_DECODE``): a small draft
  model proposes ``HOROVOD_SERVE_SPEC_DRAFT_K`` tokens per row, the
  target verifies all of them in ONE batched ``advance`` call, and the
  longest agreeing prefix plus the target's bonus token is emitted —
  greedy output is token-identical to the non-speculative path by
  construction (pinned by test). The per-step accept counts are a
  ``4*B``-byte payload published through the ``spec_sync`` hook — far
  under ``HOROVOD_LOW_LATENCY_THRESHOLD``, so when the worker wires the
  hook to its engine heartbeat the accept/reject exchange rides the
  serving-mode express lane, never the fusion buffer.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

import numpy as np

from horovod_tpu.metrics.registry import MetricsRegistry, get_registry
from horovod_tpu.obs.tracing import (DECODE_STEP, DRAFT, PREFILL, VERIFY,
                                     get_tracer, now_us)
from horovod_tpu.serve.batcher import ContinuousBatcher, InferenceRequest

StepFn = Callable[[np.ndarray, np.ndarray], np.ndarray]

# Step-latency histogram bounds (seconds): decode steps live in 100us..1s.
STEP_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0)


class ServingLoop:
    """Owns the decode thread; start/stop/drain lifecycle."""

    def __init__(self, step_fn: StepFn, batcher: ContinuousBatcher,
                 eos_token: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None,
                 idle_wait: float = 0.02,
                 cached_step: Optional["CachedStep"] = None,
                 draft_step: Optional["CachedStep"] = None,
                 spec_k: Optional[int] = None,
                 spec_sync: Optional[Callable[[np.ndarray],
                                              np.ndarray]] = None):
        self._step_fn = step_fn
        self._batcher = batcher
        self._eos = eos_token
        self._idle_wait = idle_wait
        # serving fast path: incremental decode over paged model state.
        # The block tables live in the batcher's cache, so the fast path
        # requires one (state has to be owned by the pool the admission
        # charge was made against — otherwise expiry could leak it).
        self._cached = cached_step
        if cached_step is not None and batcher.cache is None:
            raise ValueError("cached_step requires a batcher with a "
                             "PagedKVCache (state pages live in its pool)")
        self._draft = draft_step
        if draft_step is not None and cached_step is None:
            raise ValueError("speculative decoding requires cached_step")
        from horovod_tpu.common.env_registry import env_int
        self._spec_k = spec_k if spec_k is not None \
            else env_int("HOROVOD_SERVE_SPEC_DRAFT_K")
        self.spec_sync = spec_sync
        reg = registry if registry is not None else get_registry()
        self._spec_proposed = reg.counter("hvd_serve_spec_proposed_total")
        self._spec_accepted = reg.counter("hvd_serve_spec_accepted_total")
        self._inflight = reg.gauge("hvd_serve_inflight")
        self._steps = reg.counter("hvd_serve_decode_steps_total")
        self._step_seconds = reg.histogram("hvd_serve_step_seconds",
                                           buckets=STEP_BUCKETS)
        self._failures = reg.counter("hvd_serve_step_failures_total")
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServingLoop":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="hvd-serve-loop")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop pulling new work but finish everything already accepted
        (queued AND running) — the membership-change contract: a departing
        worker completes what it admitted instead of dropping it. Returns
        True when fully drained within ``timeout``."""
        self._draining.set()
        return self._idle.wait(timeout)

    # -- the loop ------------------------------------------------------------

    def _run(self):
        running: List[InferenceRequest] = []
        while not self._stop.is_set():
            running = self._batcher.fill(running)
            if not running:
                self._idle.set()
                self._inflight.set(0)
                if self._draining.is_set() and not self._batcher.pending():
                    break
                self._batcher.wait_for_work(self._idle_wait)
                continue
            self._idle.clear()
            self._inflight.set(len(running))
            self._batcher.observe_step(len(running))
            t0 = time.perf_counter()
            w0 = now_us()
            try:
                if self._cached is not None:
                    emitted = self._step_cached(running)
                else:
                    bucket = running[0].bucket
                    batch = self._batcher.max_batch
                    tokens = np.zeros((batch, bucket), np.int32)
                    # padded rows: 1 dummy token
                    lengths = np.ones(batch, np.int32)
                    for i, r in enumerate(running):
                        seq = r.tokens + r.generated
                        tokens[i, :len(seq)] = seq
                        lengths[i] = len(seq)
                    next_ids = np.asarray(self._step_fn(tokens, lengths))
                    emitted = [[int(next_ids[i])]
                               for i in range(len(running))]
                    dur = (time.perf_counter() - t0) * 1e6
                    for r in running:
                        if r.trace is not None:
                            # recompute path: every step re-runs the full
                            # forward, so each traced row gets one
                            # decode_step span per step
                            get_tracer().record(
                                r.trace, DECODE_STEP, "executor", w0, dur,
                                batch=len(running), bucket=bucket)
            except Exception as e:  # noqa: BLE001 — a broken executor must
                # fail the requests it carried, loudly, not hang them
                self._failures.inc()
                for r in running:
                    self._batcher.complete(r, "failed",
                                           f"decode step failed: {e!r}")
                running = []
                continue
            self._step_seconds.observe(time.perf_counter() - t0)
            self._steps.inc()
            now = time.monotonic()
            still: List[InferenceRequest] = []
            for i, r in enumerate(running):
                finished = False
                for tok in emitted[i]:
                    r.generated.append(int(tok))
                    if (self._eos is not None and
                            r.generated[-1] == self._eos) or \
                            len(r.generated) >= r.max_new_tokens or \
                            r.length >= r.bucket:
                        self._batcher.complete(r, "ok")
                        finished = True
                        break
                if finished:
                    continue
                if r.deadline <= now:
                    self._batcher.complete(r, "expired",
                                           "deadline passed mid-generation")
                else:
                    still.append(r)
            running = still
        self._inflight.set(0)
        self._idle.set()

    # -- the serving fast path (cached decode + speculative verify) ----------

    def _step_cached(self, running: List[InferenceRequest]):
        """One fast-path step: per-row cost is O(new tokens), not O(L).

        Prefill rows resume from their shared-prefix checkpoint and
        consume their remaining prompt tail in this step; steady rows
        consume exactly one position — or, with a draft model, propose
        ``spec_k`` tokens and have the target verify all of them in ONE
        batched ``advance`` call, emitting the longest agreeing prefix
        plus the target's bonus token (greedy-identical by
        construction). Returns the emitted token list per row."""
        cache = self._batcher.cache
        n = len(running)
        seqs = [r.tokens + r.generated for r in running]
        for i, r in enumerate(running):
            le = r.lease
            if le.state is None:
                if le.prefix_state is not None:
                    # hash hit: resume from the shared block checkpoint
                    # — this is the prefill compute the reuse pays once
                    le.state = np.asarray(le.prefix_state,
                                          np.float32).copy()
                    le.state_len = le.prefix_covered
                else:
                    le.state = self._cached.init_state(1)[0]
                    le.state_len = 0
        # tracing bookkeeping (all of it keyed on traced being non-empty,
        # so the untraced path costs one list comprehension per step): a
        # row whose state does not yet cover its prompt tail is in
        # prefill this step, the rest are steady decode
        traced = [i for i, r in enumerate(running) if r.trace is not None]
        prefill_rows = {i for i, r in enumerate(running)
                        if r.lease.state_len < len(seqs[i]) - 1}
        draft_win = verify_win = None

        # -- draft proposals (k cheap micro-steps) ---------------------------
        k = self._spec_k if self._draft is not None else 0
        ext = [list(s) for s in seqs]
        props: List[List[int]] = [[] for _ in range(n)]
        traj: List[List[np.ndarray]] = [[] for _ in range(n)]
        if k > 0:
            if traced:
                draft_t0 = (now_us(), time.perf_counter())
            steady = {i for i, r in enumerate(running)
                      if r.lease.state_len == len(seqs[i]) - 1}
            for i, r in enumerate(running):
                if r.lease.draft_state is None:
                    r.lease.draft_state = self._draft.init_state(1)[0]
                    r.lease.draft_len = 0
            for _ in range(k):
                width = max(len(e) for e in ext)
                tok = np.zeros((n, width), np.int32)
                for i, e in enumerate(ext):
                    tok[i, :len(e)] = e
                upto = np.array([len(e) for e in ext], np.int64)
                dstate = np.stack([r.lease.draft_state for r in running])
                dlen = np.array([r.lease.draft_len for r in running],
                                np.int64)
                preds, states = self._draft.advance(tok, upto, dstate,
                                                    dlen)
                for i, r in enumerate(running):
                    c = int(upto[i] - dlen[i])
                    if c > 0:
                        r.lease.draft_state = states[i, c - 1].copy()
                        r.lease.draft_len = int(upto[i])
                    if i in steady:
                        traj[i].append(r.lease.draft_state)
                        p = int(preds[i, c - 1])
                        ext[i].append(p)
                        props[i].append(p)

        if k > 0 and traced:
            draft_win = (draft_t0[0],
                         (time.perf_counter() - draft_t0[1]) * 1e6)

        # -- target verify: ONE batched advance over every row ---------------
        width = max(len(e) for e in ext)
        tok = np.zeros((n, width), np.int32)
        for i, e in enumerate(ext):
            tok[i, :len(e)] = e
        upto = np.array([len(e) for e in ext], np.int64)
        tstate = np.stack([r.lease.state for r in running])
        tlen = np.array([r.lease.state_len for r in running], np.int64)
        if traced:
            verify_t0 = (now_us(), time.perf_counter())
        preds, states = self._cached.advance(tok, upto, tstate, tlen)
        if traced:
            verify_win = (verify_t0[0],
                          (time.perf_counter() - verify_t0[1]) * 1e6)

        emitted: List[List[int]] = []
        accepts = np.zeros(n, np.int32)
        for i, r in enumerate(running):
            le = r.lease
            c = int(upto[i] - tlen[i])
            npp = len(props[i])
            base = c - npp - 1  # pred index right after the last REAL token
            a = 0
            while a < npp and props[i][a] == int(preds[i, base + a]):
                a += 1
            accepts[i] = a
            emitted.append(props[i][:a] + [int(preds[i, base + a])])
            le.state = states[i, base + a].copy()
            prev_len = le.state_len
            le.state_len = int(tlen[i]) + base + a + 1
            if npp and a < npp:
                # reject: roll the draft back to the last accepted
                # checkpoint (traj[j] covers seq + j proposals)
                le.draft_state = traj[i][a].copy() if a < len(traj[i]) \
                    else le.draft_state
                le.draft_len = len(seqs[i]) + a
            # publish the prompt's full-block boundary checkpoints as
            # shared CoW blocks on the prefill step (first crossing of
            # the prompt end)
            prompt_len = len(r.tokens)
            if cache is not None and prev_len < prompt_len:
                bt = cache.block_tokens
                bs = {}
                for end in range(bt, prompt_len + 1, bt):
                    j = end - int(tlen[i]) - 1
                    if prev_len < end and 0 <= j < c:
                        bs[end] = states[i, j]
                if bs:
                    cache.publish(le, r.tokens, bs)
            if cache is not None:
                # the emitted burst may overshoot the budget/bucket (the
                # run loop truncates at append time and completes the
                # request) — never bind past what admission charged for
                covered = min(r.length + len(emitted[i]),
                              len(r.tokens) + r.max_new_tokens, r.bucket)
                cache.bind(le, covered, le.state)
        if k > 0:
            self._spec_proposed.inc(int(sum(len(p) for p in props)))
            self._spec_accepted.inc(int(accepts.sum()))
            if self.spec_sync is not None and any(props):
                # tiny accept/reject exchange: 4*B bytes, deep under the
                # express-lane threshold
                self.spec_sync(accepts)
        if traced:
            tracer = get_tracer()
            for i in traced:
                r = running[i]
                # the target advance IS the prefill compute for rows
                # still consuming their prompt; steady rows decode (and,
                # when speculating, get the draft/verify pair too)
                if i in prefill_rows:
                    tracer.record(r.trace, PREFILL, "executor",
                                  verify_win[0], verify_win[1],
                                  tokens=int(upto[i] - tlen[i]),
                                  resumed_at=int(tlen[i]))
                else:
                    tracer.record(r.trace, DECODE_STEP, "executor",
                                  verify_win[0], verify_win[1], batch=n)
                    if props[i]:
                        tracer.record(r.trace, DRAFT, "executor",
                                      draft_win[0], draft_win[1],
                                      proposed=len(props[i]))
                        tracer.record(r.trace, VERIFY, "executor",
                                      verify_win[0], verify_win[1],
                                      proposed=len(props[i]),
                                      accepted=int(accepts[i]))
        return emitted


# ---------------------------------------------------------------------------
# cached-step contract (the serving fast path's execution interface)


class CachedStep:
    """Incremental greedy decode over an explicit, checkpointable model
    state.

    ``state_dim`` is the per-row state width H. :meth:`advance` consumes
    token positions ``state_len[b]..upto[b]-1`` of row ``b`` and returns,
    for each consumed position, the greedy next-token prediction and the
    state checkpoint *after* consuming it. The state after ``p`` tokens
    is a pure function of those ``p`` tokens — which is exactly what
    makes block-boundary checkpoints shareable across requests
    (hash-based prefix reuse) and eviction loss-free (re-derivable).

    Rows may consume different counts; ``A = max(upto - state_len)`` and
    short rows are right-padded (their padded preds/states are garbage —
    callers index by each row's own consumed count).
    """

    state_dim: int = 1

    def init_state(self, batch: int) -> np.ndarray:
        return np.zeros((batch, self.state_dim), np.float32)

    def advance(self, tokens: np.ndarray, upto: np.ndarray,
                state: np.ndarray, state_len: np.ndarray):
        raise NotImplementedError


class _ToyCachedStep(CachedStep):
    """Cached twin of :func:`make_toy_step`: the model state is the
    running token sum, so ``pred after p tokens = (sum + p) % vocab`` —
    bit-identical to the recompute path, with O(1) per-token cost."""

    state_dim = 1

    def __init__(self, vocab: int = 256):
        self.vocab = vocab

    def advance(self, tokens, upto, state, state_len):
        b, L = tokens.shape
        a = int(max(1, (upto - state_len).max()))
        preds = np.zeros((b, a), np.int32)
        states = np.zeros((b, a, 1), np.float32)
        s = state[:, 0].astype(np.int64).copy()
        pos = state_len.astype(np.int64).copy()
        for j in range(a):
            live = pos < upto
            tok = tokens[np.arange(b), np.minimum(pos, L - 1)]
            s = np.where(live, s + tok, s)
            pos = np.where(live, pos + 1, pos)
            preds[:, j] = (s + pos) % self.vocab
            states[:, j, 0] = s
        return preds, states


def make_toy_cached_step(vocab: int = 256) -> CachedStep:
    return _ToyCachedStep(vocab)


def make_toy_draft_step(vocab: int = 256, wrong_every: int = 0) -> CachedStep:
    """Draft twin of the toy model for speculative-decode tests: agrees
    with the target except (deterministically) every ``wrong_every``-th
    consumed position, so acceptance AND rejection paths both exercise.
    ``wrong_every=0`` is a perfect draft (always accepts)."""
    base = _ToyCachedStep(vocab)
    if not wrong_every:
        return base

    class _Wrong(CachedStep):
        state_dim = 1

        def advance(self, tokens, upto, state, state_len):
            preds, states = base.advance(tokens, upto, state, state_len)
            # perturb predictions at positions where (consumed count)
            # hits the wrong_every stride — a function of state_len so
            # it is deterministic and replayable
            b, a = preds.shape
            for j in range(a):
                at = state_len + j + 1
                bad = (at % wrong_every) == 0
                preds[:, j] = np.where(bad, (preds[:, j] + 1) % vocab,
                                       preds[:, j])
            return preds, states

    return _Wrong()


class _RnnCachedStep(CachedStep):
    """Recurrent LM with explicit state: ``h' = tanh(h W + E[tok])``,
    ``logits = h' E^T``. Same float-op order on the cached and recompute
    paths, so greedy tokens are bit-identical between them."""

    def __init__(self, embed: np.ndarray, w: np.ndarray):
        self.embed = embed.astype(np.float32)
        self.w = w.astype(np.float32)
        self.state_dim = w.shape[0]

    def advance(self, tokens, upto, state, state_len):
        b, L = tokens.shape
        a = int(max(1, (upto - state_len).max()))
        preds = np.zeros((b, a), np.int32)
        states = np.zeros((b, a, self.state_dim), np.float32)
        h = state.astype(np.float32).copy()
        pos = state_len.astype(np.int64).copy()
        for j in range(a):
            live = pos < upto
            tok = tokens[np.arange(b), np.minimum(pos, L - 1)]
            h_new = np.tanh(h @ self.w + self.embed[tok])
            h = np.where(live[:, None], h_new, h)
            pos = np.where(live, pos + 1, pos)
            preds[:, j] = np.argmax(h @ self.embed.T, axis=-1)
            states[:, j] = h
        return preds, states


def make_rnn_lm_step(hidden: int = 64, vocab: int = 256, seed: int = 0,
                     draft_levels: int = 24):
    """Build the fast-path reference LM: ``(step_fn, cached, draft,
    info)``.

    ``step_fn`` is the classic recompute :data:`StepFn` (derived from the
    same weights by advancing from the zero state every call — the
    "today's batcher" baseline the BENCH ``serving_fastpath`` block
    measures against). ``cached`` is the incremental :class:`CachedStep`.
    ``draft`` is the weight-quantized target (``draft_levels`` uniform
    levels per tensor — the int8-style cheap twin): its argmax mostly
    agrees with the target, which is what gives speculation a usable
    accept rate without a trained model."""
    rng = np.random.RandomState(seed)
    embed = (rng.randn(vocab, hidden) * 0.5).astype(np.float32)
    w = (rng.randn(hidden, hidden) * (0.9 / np.sqrt(hidden))) \
        .astype(np.float32)
    cached = _RnnCachedStep(embed, w)

    def quant(x):
        s = np.abs(x).max() / draft_levels
        return (np.round(x / s) * s).astype(np.float32)

    draft = _RnnCachedStep(quant(embed), quant(w))

    def step_fn(tokens: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        b = tokens.shape[0]
        preds, _ = cached.advance(
            tokens, lengths.astype(np.int64),
            cached.init_state(b), np.zeros(b, np.int64))
        idx = np.maximum(lengths - 1, 0)
        return preds[np.arange(b), np.minimum(idx, preds.shape[1] - 1)] \
            .astype(np.int32)

    info = {"hidden": hidden, "vocab": vocab, "seed": seed,
            "draft": f"uniform-quantized target ({draft_levels} levels)"}
    return step_fn, cached, draft, info


# ---------------------------------------------------------------------------
# step functions


def make_toy_step(vocab: int = 256) -> StepFn:
    """Deterministic numpy model: next token = (sum of live tokens +
    length) mod vocab. Zero dependencies and microsecond steps — the
    fixture for batcher/router/frontend tests and for subprocess serve
    workers where importing jax would dominate startup."""

    def step(tokens: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        b, L = tokens.shape
        mask = np.arange(L)[None, :] < lengths[:, None]
        s = (tokens * mask).sum(axis=1) + lengths
        return (s % vocab).astype(np.int32)

    return step


def _resolve_compression(compression):
    if compression in (None, "none", ""):
        return None
    if compression == "int8":
        from horovod_tpu.jax.compression import Compression
        return Compression.int8
    return compression  # a Compressor class


def make_tp_lm_step(mesh=None, *, vocab: int = 256, hidden: int = 64,
                    mlp_dim: int = 256, layers: int = 2, seed: int = 0,
                    compression=None):
    """Build a greedy-decode step over a small tensor-parallel decoder.

    Returns ``(step_fn, info)``. The model is embeddings → ``layers`` ×
    [LayerNorm → TP MLP (column/row parallel over the ``model`` axis) →
    residual] → LayerNorm → tied logits, with the per-layer row-parallel
    reduction in the wire format picked by ``compression`` (``None``/
    ``"none"`` → fp32 psum, ``"int8"`` → EQuARX quantized allreduce).
    Weights are deterministic from ``seed`` so every rank (and the
    bit-exactness tests) build identical shards.

    ``info`` carries the activation wire-byte accounting
    (:func:`activation_wire_report`) — the BENCH ``serving`` block's
    int8-vs-fp32 savings line."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.parallel import mesh as mesh_lib
    from horovod_tpu.parallel.tp import tp_mlp_inference

    comp = _resolve_compression(compression)
    if mesh is None:
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=1, model=len(jax.devices())))
    world = int(np.prod([mesh.shape[a] for a in ("model",)]))

    rng = np.random.RandomState(seed)
    embed = jnp.asarray(rng.randn(vocab, hidden) * 0.05, jnp.float32)
    ws = []
    for _ in range(layers):
        ws.append(jnp.asarray(rng.randn(hidden, mlp_dim) * 0.05,
                              jnp.float32))
        ws.append(jnp.asarray(rng.randn(mlp_dim, hidden) * 0.05,
                              jnp.float32))

    def _ln(x):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-6)

    def local(tokens, lengths, embed, *ws):
        x = embed[tokens]  # [B, L, d]
        for li in range(layers):
            w_in, w_out = ws[2 * li], ws[2 * li + 1]
            x = x + tp_mlp_inference(_ln(x), w_in, w_out,
                                     activation=jnp.tanh, axis="model",
                                     compression=comp)
        logits = jnp.einsum("bld,vd->blv", _ln(x), embed)
        idx = jnp.clip(lengths - 1, 0, tokens.shape[1] - 1)
        last = jnp.take_along_axis(
            logits, idx[:, None, None], axis=1)[:, 0]  # [B, V]
        return jnp.argmax(last, axis=-1).astype(jnp.int32)

    w_specs = []
    for _ in range(layers):
        w_specs.append(P(None, "model"))  # column-parallel up-projection
        w_specs.append(P("model", None))  # row-parallel down-projection
    mapped = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(), P(), *w_specs),
        out_specs=P(), check_vma=False)
    jitted = jax.jit(mapped)

    def step_fn(tokens: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        return np.asarray(jitted(jnp.asarray(tokens, jnp.int32),
                                 jnp.asarray(lengths, jnp.int32),
                                 embed, *ws))

    info = {
        "vocab": vocab, "hidden": hidden, "mlp_dim": mlp_dim,
        "layers": layers, "tp_world": world,
        "compression": "int8" if comp is not None and
        getattr(comp, "quantized", False) else "none",
        "wire": activation_wire_report(hidden, layers, world),
    }
    return step_fn, info


def activation_wire_report(hidden: int, layers: int, world: int) -> dict:
    """Per-token activation wire bytes of the TP forward (one row-parallel
    reduction of ``hidden`` elements per layer) in fp32 vs int8 — the
    measured-savings line of the BENCH ``serving`` block."""
    from horovod_tpu.jax.compression import Compression
    from horovod_tpu.parallel.tp import tp_activation_wire_bytes
    n = hidden * layers
    fp32 = tp_activation_wire_bytes(n, world, None)
    int8 = tp_activation_wire_bytes(n, world, Compression.int8)
    return {
        "world": world,
        "reduced_elems_per_token": n,
        "fp32_bytes_per_token": fp32,
        "int8_bytes_per_token": int8,
        "int8_savings_x": round(fp32 / int8, 2) if int8 else None,
    }
