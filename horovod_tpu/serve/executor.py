"""Decode loop + tensor-parallel inference execution.

The serving loop is a single consumer thread over a
:class:`~horovod_tpu.serve.batcher.ContinuousBatcher`: every iteration it
(1) frees finished slots and admits queued same-bucket requests into them,
(2) runs ONE decode step for the whole in-flight batch, (3) appends each
request's next token and completes any that reached their budget, EOS, or
deadline. Continuous batching falls out of doing admission at every step
boundary rather than per batch.

``step_fn`` is the execution contract::

    step_fn(tokens [B, L] int32, lengths [B] int32) -> next_token [B] int

with ``B`` fixed at ``max_batch`` (inactive rows padded) and ``L`` the
batch's bucket — so each bucket compiles exactly one executable.

Two built-in step functions:

- :func:`make_toy_step` — deterministic numpy-only model for tests and
  subprocess serve workers (no jax import, instant startup);
- :func:`make_tp_lm_step` — a tensor-parallel decoder over the ``model``
  mesh axis whose per-layer row-parallel reduction rides the EQuARX int8
  quantized allreduce when ``compression="int8"``
  (:func:`horovod_tpu.parallel.tp.tp_mlp_inference`). This is the int8
  *activation* path the ROADMAP calls out: PR 1 built the quantized
  collectives for gradients; serving is where they meet activations.

Decode here is prefill-style recompute (the full forward re-runs per
token over the padded bucket). That keeps shapes static and the executor
tiny; a KV-cache is an orthogonal follow-up and does not change any
interface above ``step_fn``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

import numpy as np

from horovod_tpu.metrics.registry import MetricsRegistry, get_registry
from horovod_tpu.serve.batcher import ContinuousBatcher, InferenceRequest

StepFn = Callable[[np.ndarray, np.ndarray], np.ndarray]

# Step-latency histogram bounds (seconds): decode steps live in 100us..1s.
STEP_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0)


class ServingLoop:
    """Owns the decode thread; start/stop/drain lifecycle."""

    def __init__(self, step_fn: StepFn, batcher: ContinuousBatcher,
                 eos_token: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None,
                 idle_wait: float = 0.02):
        self._step_fn = step_fn
        self._batcher = batcher
        self._eos = eos_token
        self._idle_wait = idle_wait
        reg = registry if registry is not None else get_registry()
        self._inflight = reg.gauge("hvd_serve_inflight")
        self._steps = reg.counter("hvd_serve_decode_steps_total")
        self._step_seconds = reg.histogram("hvd_serve_step_seconds",
                                           buckets=STEP_BUCKETS)
        self._failures = reg.counter("hvd_serve_step_failures_total")
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServingLoop":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="hvd-serve-loop")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop pulling new work but finish everything already accepted
        (queued AND running) — the membership-change contract: a departing
        worker completes what it admitted instead of dropping it. Returns
        True when fully drained within ``timeout``."""
        self._draining.set()
        return self._idle.wait(timeout)

    # -- the loop ------------------------------------------------------------

    def _run(self):
        running: List[InferenceRequest] = []
        while not self._stop.is_set():
            running = self._batcher.fill(running)
            if not running:
                self._idle.set()
                self._inflight.set(0)
                if self._draining.is_set() and not self._batcher.pending():
                    break
                self._batcher.wait_for_work(self._idle_wait)
                continue
            self._idle.clear()
            self._inflight.set(len(running))
            self._batcher.observe_step(len(running))
            bucket = running[0].bucket
            batch = self._batcher.max_batch
            tokens = np.zeros((batch, bucket), np.int32)
            lengths = np.ones(batch, np.int32)  # padded rows: 1 dummy token
            for i, r in enumerate(running):
                seq = r.tokens + r.generated
                tokens[i, :len(seq)] = seq
                lengths[i] = len(seq)
            t0 = time.perf_counter()
            try:
                next_ids = np.asarray(self._step_fn(tokens, lengths))
            except Exception as e:  # noqa: BLE001 — a broken executor must
                # fail the requests it carried, loudly, not hang them
                self._failures.inc()
                for r in running:
                    self._batcher.complete(r, "failed",
                                           f"decode step failed: {e!r}")
                running = []
                continue
            self._step_seconds.observe(time.perf_counter() - t0)
            self._steps.inc()
            now = time.monotonic()
            still: List[InferenceRequest] = []
            for i, r in enumerate(running):
                r.generated.append(int(next_ids[i]))
                if (self._eos is not None and
                        r.generated[-1] == self._eos) or \
                        len(r.generated) >= r.max_new_tokens or \
                        r.length >= r.bucket:
                    self._batcher.complete(r, "ok")
                elif r.deadline <= now:
                    self._batcher.complete(r, "expired",
                                           "deadline passed mid-generation")
                else:
                    still.append(r)
            running = still
        self._inflight.set(0)
        self._idle.set()


# ---------------------------------------------------------------------------
# step functions


def make_toy_step(vocab: int = 256) -> StepFn:
    """Deterministic numpy model: next token = (sum of live tokens +
    length) mod vocab. Zero dependencies and microsecond steps — the
    fixture for batcher/router/frontend tests and for subprocess serve
    workers where importing jax would dominate startup."""

    def step(tokens: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        b, L = tokens.shape
        mask = np.arange(L)[None, :] < lengths[:, None]
        s = (tokens * mask).sum(axis=1) + lengths
        return (s % vocab).astype(np.int32)

    return step


def _resolve_compression(compression):
    if compression in (None, "none", ""):
        return None
    if compression == "int8":
        from horovod_tpu.jax.compression import Compression
        return Compression.int8
    return compression  # a Compressor class


def make_tp_lm_step(mesh=None, *, vocab: int = 256, hidden: int = 64,
                    mlp_dim: int = 256, layers: int = 2, seed: int = 0,
                    compression=None):
    """Build a greedy-decode step over a small tensor-parallel decoder.

    Returns ``(step_fn, info)``. The model is embeddings → ``layers`` ×
    [LayerNorm → TP MLP (column/row parallel over the ``model`` axis) →
    residual] → LayerNorm → tied logits, with the per-layer row-parallel
    reduction in the wire format picked by ``compression`` (``None``/
    ``"none"`` → fp32 psum, ``"int8"`` → EQuARX quantized allreduce).
    Weights are deterministic from ``seed`` so every rank (and the
    bit-exactness tests) build identical shards.

    ``info`` carries the activation wire-byte accounting
    (:func:`activation_wire_report`) — the BENCH ``serving`` block's
    int8-vs-fp32 savings line."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.parallel import mesh as mesh_lib
    from horovod_tpu.parallel.tp import tp_mlp_inference

    comp = _resolve_compression(compression)
    if mesh is None:
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=1, model=len(jax.devices())))
    world = int(np.prod([mesh.shape[a] for a in ("model",)]))

    rng = np.random.RandomState(seed)
    embed = jnp.asarray(rng.randn(vocab, hidden) * 0.05, jnp.float32)
    ws = []
    for _ in range(layers):
        ws.append(jnp.asarray(rng.randn(hidden, mlp_dim) * 0.05,
                              jnp.float32))
        ws.append(jnp.asarray(rng.randn(mlp_dim, hidden) * 0.05,
                              jnp.float32))

    def _ln(x):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-6)

    def local(tokens, lengths, embed, *ws):
        x = embed[tokens]  # [B, L, d]
        for li in range(layers):
            w_in, w_out = ws[2 * li], ws[2 * li + 1]
            x = x + tp_mlp_inference(_ln(x), w_in, w_out,
                                     activation=jnp.tanh, axis="model",
                                     compression=comp)
        logits = jnp.einsum("bld,vd->blv", _ln(x), embed)
        idx = jnp.clip(lengths - 1, 0, tokens.shape[1] - 1)
        last = jnp.take_along_axis(
            logits, idx[:, None, None], axis=1)[:, 0]  # [B, V]
        return jnp.argmax(last, axis=-1).astype(jnp.int32)

    w_specs = []
    for _ in range(layers):
        w_specs.append(P(None, "model"))  # column-parallel up-projection
        w_specs.append(P("model", None))  # row-parallel down-projection
    mapped = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(), P(), *w_specs),
        out_specs=P(), check_vma=False)
    jitted = jax.jit(mapped)

    def step_fn(tokens: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        return np.asarray(jitted(jnp.asarray(tokens, jnp.int32),
                                 jnp.asarray(lengths, jnp.int32),
                                 embed, *ws))

    info = {
        "vocab": vocab, "hidden": hidden, "mlp_dim": mlp_dim,
        "layers": layers, "tp_world": world,
        "compression": "int8" if comp is not None and
        getattr(comp, "quantized", False) else "none",
        "wire": activation_wire_report(hidden, layers, world),
    }
    return step_fn, info


def activation_wire_report(hidden: int, layers: int, world: int) -> dict:
    """Per-token activation wire bytes of the TP forward (one row-parallel
    reduction of ``hidden`` elements per layer) in fp32 vs int8 — the
    measured-savings line of the BENCH ``serving`` block."""
    from horovod_tpu.jax.compression import Compression
    from horovod_tpu.parallel.tp import tp_activation_wire_bytes
    n = hidden * layers
    fp32 = tp_activation_wire_bytes(n, world, None)
    int8 = tp_activation_wire_bytes(n, world, Compression.int8)
    return {
        "world": world,
        "reduced_elems_per_token": n,
        "fp32_bytes_per_token": fp32,
        "int8_bytes_per_token": int8,
        "int8_savings_x": round(fp32 / int8, 2) if int8 else None,
    }
