"""Load generation + latency microbenches behind the BENCH ``serving``
block.

Two instruments:

- :func:`run_load` / :func:`run_points` — open-loop offered load against
  any ``submit(payload) -> result`` callable (the local frontend handler,
  an HTTP client, the router). Open-loop matters: a closed loop slows its
  own arrival rate when the server saturates and can never show the
  backpressure knee; here arrivals keep coming at the offered rate and the
  rejected/expired counts + p99 show graceful degradation (bounded queue,
  fast 429s) instead of collapse.

- :func:`small_allreduce_latency` — the small-tensor cost-cliff
  regression microbench: the p50 latency of a sub-threshold (≤ 4 KiB)
  allreduce issued alongside a bulk tensor, measured with
  ``HOROVOD_SERVING_MODE`` off (the small tensor fuses behind the bulk
  one and pays its exec time) vs on (express lane). This is the measured
  evidence that serving mode removed the cliff.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


def percentile(values: Sequence[float], q: float) -> Optional[float]:
    if not values:
        return None
    xs = sorted(values)
    idx = min(int(q * len(xs)), len(xs) - 1)
    return xs[idx]


def run_load(submit: Callable[[dict], dict], offered_qps: float,
             duration_sec: float, make_payload: Callable[[int], dict],
             max_dispatchers: int = 32) -> Dict[str, object]:
    """Offer ``offered_qps`` for ``duration_sec`` against ``submit``.

    ``submit`` must be blocking and return a result dict with a
    ``status`` key (``ok``/``rejected``/``expired``/``failed``); raising
    counts as ``failed``. A fixed dispatcher pool drains the arrival
    schedule; when the pool can't keep up (server slower than offered
    load), arrivals back up client-side and the achieved rate drops —
    which is the saturation signal, reported honestly rather than by
    slowing the offered clock."""
    n = max(1, int(offered_qps * duration_sec))
    interval = 1.0 / offered_qps
    t0 = time.monotonic()
    schedule = [t0 + i * interval for i in range(n)]
    cursor = {"i": 0}
    lock = threading.Lock()
    latencies: List[float] = []
    counts = {"ok": 0, "rejected": 0, "expired": 0, "failed": 0}

    def dispatch():
        while True:
            with lock:
                i = cursor["i"]
                if i >= n:
                    return
                cursor["i"] = i + 1
                due = schedule[i]
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            start = time.monotonic()
            try:
                result = submit(make_payload(i))
                status = result.get("status", "failed")
            except Exception:  # noqa: BLE001 — a refused dispatch is a
                status = "failed"  # data point, not a bench crash
            took = time.monotonic() - start
            with lock:
                counts[status] = counts.get(status, 0) + 1
                if status == "ok":
                    latencies.append(took)

    workers = [threading.Thread(target=dispatch, daemon=True)
               for _ in range(min(max_dispatchers, n))]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    wall = time.monotonic() - t0
    # a submit that returned a non-terminal status (e.g. a client-side
    # wait timeout handing back "running") must not vanish from the
    # accounting: requests == ok + rejected + expired + failed + unsettled
    unsettled = sum(v for k, v in counts.items()
                    if k not in ("ok", "rejected", "expired", "failed"))
    return {
        "offered_qps": round(offered_qps, 2),
        "duration_sec": round(wall, 2),
        "requests": n,
        "completed_ok": counts["ok"],
        "rejected": counts["rejected"],
        "expired": counts["expired"],
        "failed": counts["failed"],
        "unsettled": unsettled,
        "achieved_qps": round(counts["ok"] / wall, 2) if wall > 0 else 0.0,
        "p50_ms": round(percentile(latencies, 0.50) * 1e3, 2)
        if latencies else None,
        "p99_ms": round(percentile(latencies, 0.99) * 1e3, 2)
        if latencies else None,
    }


def shared_prefix_trace(seed: int = 0, requests: int = 256,
                        tenants: int = 4, prefix_len: int = 96,
                        tail_len: int = 16, max_new_tokens: int = 16,
                        vocab: int = 256,
                        tenant_mix: Optional[Sequence[float]] = None
                        ) -> List[dict]:
    """Seeded, replayable shared-prefix request trace — the first brick
    of the ROADMAP trace-driven loadgen item, shared by the BENCH
    ``serving_fastpath`` block, the smoke, and the tests.

    Each tenant has one fixed ``prefix_len``-token system prompt; every
    request is that prefix plus a fresh ``tail_len``-token user turn.
    ``tenant_mix`` weights the tenant draw (default is zipf-ish: tenant 0
    dominates — the million-users-one-system-prompt shape where prefix
    reuse pays). Identical ``(seed, knobs)`` always reproduce the exact
    same token streams, so a bench regression is re-runnable bit-for-bit.
    """
    rng = np.random.RandomState(seed)
    prefixes = [rng.randint(0, vocab, prefix_len).tolist()
                for _ in range(tenants)]
    mix = np.asarray(tenant_mix if tenant_mix is not None
                     else [1.0 / (i + 1) for i in range(tenants)], float)
    mix = mix / mix.sum()
    out: List[dict] = []
    for _ in range(requests):
        t = int(rng.choice(tenants, p=mix))
        tail = rng.randint(0, vocab, tail_len).tolist()
        out.append({"tenant": f"tenant{t}",
                    "tokens": prefixes[t] + tail,
                    "max_new_tokens": int(max_new_tokens)})
    return out


def trace_payload_fn(trace: Sequence[dict]) -> Callable[[int], dict]:
    """Adapter: a replayable trace as the ``make_payload`` argument of
    :func:`run_load` (wraps around when offered load outruns the trace)."""

    def make_payload(i: int) -> dict:
        return dict(trace[i % len(trace)])

    return make_payload


def run_points(submit: Callable[[dict], dict],
               make_payload: Callable[[int], dict],
               points_qps: Sequence[float],
               duration_sec: float = 3.0) -> List[Dict[str, object]]:
    """One :func:`run_load` window per offered-load point (the BENCH
    serving sweep: at least one point past saturation so the JSON shows
    backpressure, not collapse)."""
    return [run_load(submit, qps, duration_sec, make_payload)
            for qps in points_qps]


# ---------------------------------------------------------------------------
# small-tensor latency microbench (the serving-mode cost-cliff regression)


def _exec_callback(lib, session, dtype_ids):
    """Data-plane callback sized from the response metadata — runs the real
    loopback combine so bulk responses cost real exec time."""

    def cb(resp):
        elems = 0
        for shape in resp.get("shapes", []):
            n = 1
            for d in shape:
                n *= d
            elems += n
        buf = np.ones(max(elems, 1), np.float32)
        return lib.hvdtpu_data_allreduce(
            session._session, buf.ctypes.data, buf.size,
            dtype_ids["float32"], 0, 1.0, 1.0)

    return cb


def small_allreduce_latency(serving_mode: bool, ranks: int = 2,
                            small_elems: int = 256,
                            big_elems: int = 1 << 22,
                            iters: int = 15) -> Dict[str, object]:
    """p50/mean latency (ms) of a small allreduce (``small_elems`` fp32 —
    1 KiB at the default, well under HOROVOD_LOW_LATENCY_THRESHOLD) whose
    negotiation cycle also carries a bulk ``big_elems`` tensor.

    Without serving mode the two fuse (same reduce params, under the
    fusion threshold) and the small tensor's completion waits on the fused
    exec; with it, the small response rides the express lane ahead of the
    bulk one. In-process loopback ranks, so this measures engine protocol
    + host data plane, no network."""
    from horovod_tpu.common.env_registry import env_raw
    from horovod_tpu.engine import bindings
    prev = env_raw("HOROVOD_SERVING_MODE")
    os.environ["HOROVOD_SERVING_MODE"] = "1" if serving_mode else "0"
    try:
        group = f"servebench-{uuid.uuid4().hex[:8]}"
        sessions = [bindings.EngineSession(
            rank=r, size=ranks, transport="loopback", group=group,
            cycle_time_ms=1.0, stall_warning_sec=60.0)
            for r in range(ranks)]
        lib = bindings.load_library()
        for s in sessions:
            s.set_execute_callback(_exec_callback(lib, s,
                                                  bindings.DTYPE_IDS))
        small_lat: List[float] = []
        barrier = threading.Barrier(ranks)

        def run(rank: int, s):
            from horovod_tpu.engine.bindings import OP_ALLREDUCE
            for i in range(iters):
                barrier.wait()
                # small submitted first so both tensors deterministically
                # land in the same negotiation cycle (the fused-mode cliff
                # needs them co-negotiated; queue order does not affect
                # fusion)
                t0 = time.perf_counter()
                hs = s.enqueue(f"small.{i}", OP_ALLREDUCE, "float32",
                               [small_elems])
                hb = s.enqueue(f"bulk.{i}", OP_ALLREDUCE, "float32",
                               [big_elems])
                s.wait(hs, timeout=60.0)
                dt = time.perf_counter() - t0
                if rank == 0:
                    small_lat.append(dt)
                s.wait(hb, timeout=60.0)

        threads = [threading.Thread(target=run, args=(r, s), daemon=True)
                   for r, s in enumerate(sessions)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        counters = sessions[0].metrics().get("counters", {})
        for s in sessions:
            s._lib.hvdtpu_shutdown(s._session)
        for s in sessions:
            s.destroy()
        return {
            "serving_mode": serving_mode,
            "small_bytes": small_elems * 4,
            "bulk_bytes": big_elems * 4,
            "iters": iters,
            "p50_ms": round(percentile(small_lat, 0.5) * 1e3, 3),
            "mean_ms": round(float(np.mean(small_lat)) * 1e3, 3),
            "low_latency_responses":
                counters.get("low_latency_responses", 0),
            "fused_responses": counters.get("fused_responses", 0),
        }
    finally:
        if prev is None:
            os.environ.pop("HOROVOD_SERVING_MODE", None)
        else:
            os.environ["HOROVOD_SERVING_MODE"] = prev


def small_tensor_cliff_report(**kwargs) -> Dict[str, object]:
    """The BENCH line: small-allreduce latency with serving mode off vs on,
    plus the speedup — the regression number for the fusion-cycle cost
    cliff satellite."""
    off = small_allreduce_latency(False, **kwargs)
    on = small_allreduce_latency(True, **kwargs)
    # Mean is the headline: in fused mode the co-negotiation race means
    # only a fraction of iterations actually fuse (the rest complete fast
    # solo), so the p50 can land on the fast side while the mean carries
    # the cliff iterations honestly.
    mean = round(off["mean_ms"] / on["mean_ms"], 2) if on["mean_ms"] \
        else None
    p50 = round(off["p50_ms"] / on["p50_ms"], 2) if on["p50_ms"] else None
    return {"fused_mode": off, "serving_mode": on,
            "mean_speedup_x": mean, "p50_speedup_x": p50}
