"""Block-paged KV cache with hash-based prefix reuse.

The serving fast path's memory manager, owned by the batcher. The same
idea as the engine's fusion buffer — amortize a fixed cost across many
small units — applied to prefill compute and cache memory:

- **fixed-size blocks**: the per-request cache is a *block table* (a list
  of block ids), each block covering ``HOROVOD_SERVE_KV_BLOCK_TOKENS``
  token positions. A block's payload is the model state checkpoint at the
  block's end boundary (for the attention models the blocks would hold
  K/V pages; the executor's cached-step contract only ever needs the
  boundary checkpoint, which is what makes eviction and sharing exact);
- **bounded pool, charged at admission**: :meth:`PagedKVCache.admit`
  charges the worst-case block count (prompt + token budget, minus any
  shared prefix blocks already resident) against
  ``HOROVOD_SERVE_KV_POOL_BLOCKS``. A request that cannot get blocks is
  rejected *now* (429-shaped :class:`CacheExhausted` — backpressure, not
  an OOM twenty steps later). Charged-but-queued requests own capacity
  only; physical block ids are bound lazily by the decode loop, so a
  request that expires in the queue provably never allocated;
- **hash-based prefix reuse (CoW)**: full prompt blocks are content-
  hashed; the first request to prefill a prefix publishes its boundary
  checkpoints as *shared* blocks, and later admissions with the same
  prefix incref them instead of charging new blocks — a thousand requests
  with the same system prompt pay prefill once. Shared blocks are
  refcounted and never written after publication (copy-on-write: a
  request's own generated tokens always land in private blocks);
- **LRU eviction over finished/expired**: a shared block whose refcount
  drops to zero stays resident as reuse capital and joins an LRU list;
  admission evicts LRU zero-ref blocks when the free pool alone cannot
  cover a charge. Live requests (refcount > 0) are never evicted — the
  no-use-after-free rule :class:`~horovod_tpu.verify.specs.PagedCacheSpec`
  model-checks.

Accounting invariant (the spec's conservation law, also asserted by the
churn regression test)::

    pool_blocks == free + charged(private) + resident(shared)

at every step boundary — across queued expiry, running expiry (freed at
the boundary where the partial output is returned), drain, and a chaos
kill of the serving worker.

All gauges/counters land in ``hvd_serve_cache_*`` so ``hvd-top
--serving`` (HIT%/BLOCKS/REUSE columns), ``GET /stats`` and the BENCH
``serving_fastpath`` block read the same numbers.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from horovod_tpu.common.env_registry import env_bool, env_int
from horovod_tpu.metrics.registry import MetricsRegistry, get_registry
from horovod_tpu.obs.tracing import CACHE_LOOKUP, get_tracer


class CacheExhausted(RuntimeError):
    """The bounded block pool cannot cover an admission charge even after
    evicting every zero-ref shared block — 429-shaped backpressure; the
    caller sheds or retries elsewhere, nobody OOMs mid-decode."""


def blocks_for(tokens: int, block_tokens: int) -> int:
    """Blocks needed to cover ``tokens`` positions (ceil division)."""
    return max(0, (int(tokens) + block_tokens - 1) // block_tokens)


def prefix_hash(tokens: Sequence[int], parent: str = "") -> str:
    """Content hash of one full prefix block, chained through its parent
    block's hash — so a block is only ever shared between requests whose
    *entire* prefix up to that boundary is identical, not merely the
    block's own span."""
    h = hashlib.sha256(parent.encode())
    h.update(np.asarray(list(tokens), np.int64).tobytes())
    return h.hexdigest()[:24]


class _Block:
    """One pool block. ``hash`` is None for private (single-owner) blocks
    and the chained content hash for shared prefix blocks; ``state`` is
    the model-state checkpoint at the block's end boundary."""

    __slots__ = ("id", "hash", "refs", "state")

    def __init__(self, block_id: int):
        self.id = block_id
        self.hash: Optional[str] = None
        self.refs = 0
        self.state: Optional[np.ndarray] = None


class CacheLease:
    """A request's slice of the pool, created at admission.

    ``charged`` blocks of capacity are owned from :meth:`PagedKVCache.admit`
    until exactly one of :meth:`PagedKVCache.release` (never ran) or
    :meth:`PagedKVCache.free` (ran). ``shared`` lists the increfed resident
    prefix blocks; ``table`` is the private block table, bound lazily by
    the decode loop as the sequence crosses block boundaries (a queued
    request's table is always empty — the expiry-split invariant).
    """

    __slots__ = ("charged", "shared", "table", "prefix_state",
                 "prefix_covered", "state", "state_len", "draft_state",
                 "draft_len", "closed")

    def __init__(self, charged: int, shared: List[_Block],
                 prefix_state: Optional[np.ndarray], prefix_covered: int):
        self.charged = int(charged)
        self.shared = shared                  # increfed shared blocks
        self.table: List[int] = []            # bound private block ids
        self.prefix_state = prefix_state      # checkpoint to resume from
        self.prefix_covered = int(prefix_covered)  # tokens it covers
        # decode-loop scratch (single consumer thread): current model
        # state + how many tokens it covers, plus the draft model's twin
        self.state: Optional[np.ndarray] = None
        self.state_len = 0
        self.draft_state: Optional[np.ndarray] = None
        self.draft_len = 0
        self.closed = False

    @property
    def bound(self) -> int:
        return len(self.table)


class PagedKVCache:
    """Bounded block pool + shared-prefix hash table.

    Thread contract mirrors the batcher's: any producer thread calls
    :meth:`admit` / :meth:`release` (both take the internal lock); the
    single decode-loop consumer calls :meth:`bind`, :meth:`publish` and
    :meth:`free`.
    """

    def __init__(self, block_tokens: Optional[int] = None,
                 pool_blocks: Optional[int] = None,
                 prefix_reuse: Optional[bool] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.block_tokens = block_tokens if block_tokens is not None \
            else env_int("HOROVOD_SERVE_KV_BLOCK_TOKENS")
        self.pool_blocks = pool_blocks if pool_blocks is not None \
            else env_int("HOROVOD_SERVE_KV_POOL_BLOCKS")
        self.prefix_reuse = prefix_reuse if prefix_reuse is not None \
            else env_bool("HOROVOD_SERVE_PREFIX_REUSE")
        if self.block_tokens < 1 or self.pool_blocks < 1:
            raise ValueError("block_tokens and pool_blocks must be >= 1")
        self._lock = threading.Lock()
        self._free = int(self.pool_blocks)
        self._charged = 0                      # private capacity held
        self._next_id = 0
        # shared prefix blocks: chained hash -> block; LRU order over
        # zero-ref residents (front = oldest = first evicted)
        self._shared: Dict[str, _Block] = {}
        self._lru: List[str] = []
        reg = registry if registry is not None else get_registry()
        self._g_pool = reg.gauge("hvd_serve_cache_pool_blocks")
        self._g_pool.set(self.pool_blocks)
        self._g_used = reg.gauge("hvd_serve_cache_blocks_used")
        self._g_shared = reg.gauge("hvd_serve_cache_shared_blocks")
        self._c_lookups = reg.counter("hvd_serve_cache_lookups_total")
        self._c_hits = reg.counter("hvd_serve_cache_hits_total")
        self._c_reuse = reg.counter("hvd_serve_cache_reuse_total")
        self._c_evict = reg.counter("hvd_serve_cache_evictions_total")
        self._c_exhausted = reg.counter("hvd_serve_cache_exhausted_total")
        self._c_saved = reg.counter(
            "hvd_serve_cache_prefill_tokens_saved_total")

    # -- introspection --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {"pool_blocks": self.pool_blocks, "free": self._free,
                    "charged": self._charged,
                    "shared_resident": len(self._shared),
                    "evictable": len(self._lru)}

    def balanced(self) -> bool:
        """The conservation law — True at every quiescent point."""
        with self._lock:
            return self._free + self._charged + len(self._shared) \
                == self.pool_blocks

    def _set_gauges_locked(self):
        self._g_used.set(self._charged + len(self._shared))
        self._g_shared.set(len(self._shared))

    # -- admission (producer side) --------------------------------------------

    def _prefix_blocks(self, tokens: Sequence[int]) -> List[Tuple[str,
                                                                  tuple]]:
        """(chained hash, block tokens) for each FULL block of the prompt
        — partial tail blocks are never shared (their boundary checkpoint
        does not exist)."""
        out = []
        parent = ""
        bt = self.block_tokens
        for i in range(len(tokens) // bt):
            chunk = tuple(int(t) for t in tokens[i * bt:(i + 1) * bt])
            parent = prefix_hash(chunk, parent)
            out.append((parent, chunk))
        return out

    def admit(self, tokens: Sequence[int], budget: int,
              trace: Optional[str] = None) -> CacheLease:
        """Charge the pool for a request (prompt + ``budget`` generated
        tokens) or raise :class:`CacheExhausted`.

        Resident shared prefix blocks are increfed instead of charged —
        the prefix-reuse capacity win. Eviction of zero-ref LRU blocks
        happens here, only when the free pool alone cannot cover.
        ``trace`` (a sampled trace id) emits the ``cache_lookup`` span
        covering the prefix-hash walk + pool charge."""
        with get_tracer().span(trace, CACHE_LOOKUP, "kv_cache") as sp:
            lease = self._admit(tokens, budget)
            if trace is not None:
                sp.args = dict(sp.args, charged=lease.charged,
                               shared_hits=len(lease.shared),
                               prefix_covered=lease.prefix_covered)
            return lease

    def _admit(self, tokens: Sequence[int], budget: int) -> CacheLease:
        total = blocks_for(len(tokens) + int(budget), self.block_tokens)
        with self._lock:
            shared: List[_Block] = []
            prefix_state: Optional[np.ndarray] = None
            covered = 0
            if self.prefix_reuse:
                for h, _chunk in self._prefix_blocks(tokens):
                    self._c_lookups.inc()
                    blk = self._shared.get(h)
                    if blk is None or blk.state is None:
                        break  # chained: a miss ends the shared run
                    self._c_hits.inc()
                    shared.append(blk)
                # the decode loop always recomputes at least the final
                # prompt position (it needs the prediction *after* the
                # prompt) — an exactly block-aligned prompt drops its
                # last shared block rather than resume past the end
                while shared and \
                        len(shared) * self.block_tokens >= len(tokens):
                    shared.pop()
                for blk in shared:
                    if blk.refs == 0 and blk.hash in self._lru:
                        self._lru.remove(blk.hash)
                    blk.refs += 1
                    self._c_reuse.inc()
                if shared:
                    prefix_state = shared[-1].state
                    covered = len(shared) * self.block_tokens
            need = total - len(shared)
            while self._free < need and self._lru:
                self._evict_locked()
            if self._free < need:
                for blk in shared:  # undo the increfs — nothing leaks
                    blk.refs -= 1
                    if blk.refs == 0:
                        self._lru.append(blk.hash)
                self._c_exhausted.inc()
                raise CacheExhausted(
                    f"kv cache pool exhausted: need {need} blocks, "
                    f"{self._free} free of {self.pool_blocks} "
                    f"(backpressure)")
            self._free -= need
            self._charged += need
            if covered:
                self._c_saved.inc(covered)
            self._set_gauges_locked()
            return CacheLease(need, shared, prefix_state, covered)

    def _evict_locked(self):
        h = self._lru.pop(0)
        blk = self._shared.pop(h)
        assert blk.refs == 0
        blk.state = None  # the use-after-free tripwire: a stale table
        blk.hash = None   # entry now holds a dead block
        self._free += 1
        self._c_evict.inc()

    def release(self, lease: CacheLease):
        """Undo an admission that never ran (queued expiry / shed): return
        the charge, decref shared. The lease provably never bound a block
        (``lease.table`` is empty) — the expiry-split invariant."""
        self._close(lease, ran=False)

    # -- decode loop (consumer side) ------------------------------------------

    def bind(self, lease: CacheLease, covered_tokens: int,
             state: Optional[np.ndarray] = None):
        """Bind private block ids for every newly crossed block boundary,
        checkpointing ``state`` into the newest block. Capacity was
        already charged at admission, so this never blocks and never
        fails — it just turns owned capacity into table entries."""
        want = blocks_for(covered_tokens, self.block_tokens) - \
            len(lease.shared)
        with self._lock:
            while lease.bound < want:
                if lease.bound >= lease.charged:
                    # deadline-capped requests can out-generate their
                    # charge estimate only if budget accounting broke;
                    # fail loudly rather than corrupt the pool
                    raise RuntimeError(
                        "block table outgrew the admission charge "
                        f"({lease.charged} blocks)")
                self._next_id += 1
                lease.table.append(self._next_id)
        if state is not None:
            lease.state = state

    def publish(self, lease: CacheLease, tokens: Sequence[int],
                boundary_states: Dict[int, np.ndarray]):
        """Publish the prompt's full-block boundary checkpoints as shared
        CoW blocks (``boundary_states``: tokens-covered -> state).

        The publisher's private blocks covering those boundaries convert
        to shared: its charge shrinks, the shared population grows, pool
        conservation holds exactly. Later admissions with the same prefix
        incref instead of charging. First writer wins — a concurrent
        publisher of the same hash just keeps its private blocks."""
        if not self.prefix_reuse:
            return
        with self._lock:
            converted = 0
            for i, (h, _chunk) in enumerate(self._prefix_blocks(tokens)):
                end = (i + 1) * self.block_tokens
                if end <= lease.prefix_covered:
                    continue  # resumed from this shared block already
                st = boundary_states.get(end)
                if st is None or h in self._shared:
                    continue
                # the shared block takes over the publisher's private
                # block id for this boundary when one is bound (the
                # page itself converts — CoW, not a copy), else a fresh
                # id (the publisher resumed partway and never bound it)
                if converted < len(lease.table):
                    blk = _Block(lease.table[converted])
                else:
                    self._next_id += 1
                    blk = _Block(self._next_id)
                blk.hash = h
                blk.refs = 1
                blk.state = np.array(st, copy=True)
                self._shared[h] = blk
                lease.shared.append(blk)
                converted += 1
            if converted:
                # the converted capacity moves from this lease's private
                # charge to the shared population
                take = min(converted, lease.charged)
                lease.charged -= take
                self._charged -= take
                extra = converted - take
                if extra > 0:
                    # cannot happen under charge accounting; guard the
                    # conservation law anyway
                    self._free -= extra
                del lease.table[:min(converted, len(lease.table))]
                self._set_gauges_locked()

    def free(self, lease: CacheLease):
        """Free a request that ran: private blocks return to the pool at
        the step boundary where its output (full or partial) is returned;
        shared blocks decref, and zero-ref shared blocks stay resident on
        the LRU as reuse capital."""
        self._close(lease, ran=True)

    def _close(self, lease: CacheLease, ran: bool):
        with self._lock:
            if lease.closed:
                # double-free is the PagedCacheSpec mutant class; the
                # runtime guards it idempotently AND loudly in debug
                return
            if not ran and lease.table:
                # raised BEFORE marking closed: the caller's bug must
                # stay loud, but a later free() can still settle the
                # charge instead of leaking it
                raise RuntimeError(
                    "queued request bound blocks without running "
                    "(expiry-split violation)")
            lease.closed = True
            self._free += lease.charged
            self._charged -= lease.charged
            lease.charged = 0
            lease.table.clear()
            for blk in lease.shared:
                blk.refs -= 1
                if blk.refs == 0 and blk.hash is not None:
                    self._lru.append(blk.hash)
            lease.shared = []
            lease.state = None
            lease.draft_state = None
            self._set_gauges_locked()
