from horovod_tpu.ops.fusion import fused_apply, fused_apply_tree  # noqa: F401
