"""Fused flash attention as a Pallas TPU kernel — forward AND backward.

The hot op of the transformer path (BASELINE config 3): computes
softmax(QK^T)V blockwise in VMEM with online log-sum-exp accumulation, so
the [T, T] score matrix never exists in HBM — the kernel streams K/V blocks
through the MXU and keeps the fp32 accumulators on chip.

Three design points make this the building block the rest of the framework
composes with:

- **log-sum-exp residual**: ``return_lse=True`` also returns the per-row
  lse, which is exactly what an online-softmax *merge* needs. That is how
  ``parallel/sp.py:ring_attention`` uses this kernel as its within-shard
  engine: each ring step produces (o, lse) for one K/V shard and the
  results merge exactly.
- **global position offsets**: ``q_offset``/``k_offset`` (traced scalars,
  staged into SMEM) shift the causal mask to global coordinates, so a
  sequence-sharded rank can attend its local q block against a rotating
  remote K/V shard. Blocks entirely in the future cost zero work — the k
  loop's *traced* upper bound excludes them.
- **custom VJP**: backward is two Pallas kernels (dq gridded over q tiles,
  dk/dv gridded over k tiles) recomputing probabilities from the saved lse,
  the standard flash backward. The lse output is differentiable too
  (d lse/d s = softmax prob), so gradients flow through ring-attention
  merges.

Layout: [batch, seq, heads, head_dim] in, same out; internally each
(batch, head) pair is one grid row. Pure-JAX reference semantics are tested
against in interpret mode (CPU) and the kernel compile-checks on the real
chip.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() NaN-free

# Short-sequence crossover for the auto-router (:func:`attention`). Measured
# on v5e (BENCH_r05): plain XLA dot attention beats the Pallas kernel at seq
# 128 (980 vs 820 seqs/s on BERT-Base — the score tiles are too small to
# fill the grid), flash wins from ~2k (1.5x) through 8k (3+x). Sequences
# shorter than this route to XLA; override with HOROVOD_FLASH_MIN_SEQ.
DEFAULT_FLASH_MIN_SEQ = 1024


def _pos(off_f32, base, shape, dim):
    """Global positions (fp32 — exact for T < 2^24) of a tile. The iota is
    integer (TPU's tpu.iota only produces ints) then cast."""
    iota = lax.broadcasted_iota(jnp.int32, shape, dim).astype(jnp.float32)
    return off_f32 + base + iota


def _causal_num_k(q_off, k_off, qi, block_q, block_k, num_k):
    """Traced count of k blocks a causal q tile can see: blocks entirely in
    the tile's future are excluded from the loop outright (shared by the
    forward and dq kernels — they must agree on visited blocks)."""
    max_q_pos = q_off + (qi + 1) * block_q - 1
    eff = jnp.floor((max_q_pos - k_off) / block_k) + 1
    return jnp.clip(eff, 0, num_k).astype(jnp.int32)


def _fwd_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                block_q: int, block_k: int, causal: bool, sm_scale: float,
                kv_len: int):
    qi = pl.program_id(1)
    q_off, k_off = qo_ref[0], ko_ref[0]
    # Matmuls run in the input dtype (bf16 rides the fast MXU path; fp32
    # inputs keep full precision) and accumulate in fp32 via
    # preferred_element_type — casting inputs up to fp32 would force 3-pass
    # fp32 MXU matmuls and ~30% more step time.
    q = q_ref[0]  # [block_q, d]
    d_v = v_ref.shape[-1]

    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    o = jnp.zeros((block_q, d_v), jnp.float32)
    q_pos = _pos(q_off, qi * block_q, (block_q, block_k), 0)

    def body(kj, carry):
        m, l, o = carry
        k = k_ref[0, pl.ds(kj * block_k, block_k), :]
        v = v_ref[0, pl.ds(kj * block_k, block_k), :]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            k_pos = _pos(k_off, kj * block_k, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_i = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_i)
        p = jnp.exp(s - m_new)  # rows fully at NEG_INF decay to ~0
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o = o * alpha + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, o

    num_k = kv_len // block_k
    if causal:
        num_k = _causal_num_k(q_off, k_off, qi, block_q, block_k, num_k)
    m, l, o = lax.fori_loop(0, num_k, body, (m, l, o))
    o_ref[0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)
    # lse rides a full-row (1, 1, Tq) block revisited across q tiles — TPU
    # lowering wants the last two block dims tiling-aligned or equal to the
    # array dims, which a (1, block_q) block is not.
    lse_ref[0, 0, pl.ds(qi * block_q, block_q)] = lse[:, 0]


def _bwd_dq_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   corr_ref, dq_ref, *, block_q: int, block_k: int,
                   causal: bool, sm_scale: float, kv_len: int):
    """dq for one q tile: loop k tiles, recompute p from lse, accumulate
    ds @ k. ``corr`` is (dlse - delta) precomputed on host-side JAX."""
    qi = pl.program_id(1)
    q_off, k_off = qo_ref[0], ko_ref[0]
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)][:, None]
    corr = corr_ref[0, 0, pl.ds(qi * block_q, block_q)][:, None]
    live = lse > NEG_INF / 2  # fully-masked rows produce zero grads
    q_pos = _pos(q_off, qi * block_q, (block_q, block_k), 0)

    def body(kj, dq):
        k = k_ref[0, pl.ds(kj * block_k, block_k), :]
        v = v_ref[0, pl.ds(kj * block_k, block_k), :]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        p = jnp.where(live, jnp.exp(s - lse), 0.0)
        if causal:
            k_pos = _pos(k_off, kj * block_k, (block_q, block_k), 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp + corr) * sm_scale).astype(k.dtype)
        return dq + lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)

    num_k = kv_len // block_k
    if causal:
        num_k = _causal_num_k(q_off, k_off, qi, block_q, block_k, num_k)
    dq = lax.fori_loop(0, num_k, body,
                       jnp.zeros((block_q, q.shape[-1]), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    corr_ref, dk_ref, dv_ref, *, block_q: int, block_k: int,
                    causal: bool, sm_scale: float, q_len: int):
    """dk/dv for one k tile: loop q tiles (starting past fully-causal-masked
    ones), recompute p, accumulate p^T @ do and ds^T @ q."""
    kj = pl.program_id(1)
    q_off, k_off = qo_ref[0], ko_ref[0]
    k = k_ref[0]  # [block_k, d]
    v = v_ref[0]
    k_pos = _pos(k_off, kj * block_k, (block_q, block_k), 1)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        corr = corr_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        live = lse > NEG_INF / 2
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        p = jnp.where(live, jnp.exp(s - lse), 0.0)
        if causal:
            q_pos = _pos(q_off, i * block_q, (block_q, block_k), 0)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dv = dv + lax.dot_general(p.astype(do.dtype), do,
                                  (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp + corr) * sm_scale).astype(q.dtype)
        dk = dk + lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return dk, dv

    num_q = q_len // block_q
    start = 0
    if causal:
        # first q tile whose max q position reaches this k tile's start
        min_k_pos = k_off + kj * block_k
        s0 = jnp.floor((min_k_pos - q_off) / block_q)
        start = jnp.clip(s0, 0, num_q).astype(jnp.int32)
    dk, dv = lax.fori_loop(
        start, num_q, body,
        (jnp.zeros((block_k, k.shape[-1]), jnp.float32),
         jnp.zeros((block_k, v.shape[-1]), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bh_first(x):  # [B, T, H, D] -> [B*H, T, D]
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _scalar_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q, k, v, q_off, k_off, causal, sm_scale, block_q, block_k,
           interpret):
    o, lse, _ = _flash_fwd(q, k, v, q_off, k_off, causal, sm_scale,
                           block_q, block_k, interpret)
    return o, lse


def _flash_fwd(q, k, v, q_off, k_off, causal, sm_scale, block_q, block_k,
               interpret):
    b, tq, h, d = q.shape
    tk, dv = k.shape[1], v.shape[-1]
    qb, kb, vb = _bh_first(q), _bh_first(k), _bh_first(v)
    grid = (b * h, tq // block_q)
    kernel = functools.partial(_fwd_kernel, block_q=block_q, block_k=block_k,
                               causal=causal, sm_scale=sm_scale, kv_len=tk)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _scalar_spec(), _scalar_spec(),
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, tk, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, tk, dv), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dv), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, 1, tq), lambda bh, i: (bh, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, dv), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, tq), jnp.float32),
        ],
        interpret=interpret,
    )(q_off, k_off, qb, kb, vb)
    o_out = o.reshape(b, h, tq, dv).transpose(0, 2, 1, 3)
    lse_out = lse.reshape(b, h, tq)
    return o_out, lse_out, (q, k, v, o_out, lse, q_off, k_off)


def _flash_fwd_vjp(q, k, v, q_off, k_off, causal, sm_scale, block_q,
                   block_k, interpret):
    o, lse_out, res = _flash_fwd(q, k, v, q_off, k_off, causal, sm_scale,
                                 block_q, block_k, interpret)
    return (o, lse_out), res


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, res, cots):
    q, k, v, o, lse, q_off, k_off = res
    do, dlse = cots
    b, tq, h, d = q.shape
    tk, dv = k.shape[1], v.shape[-1]
    dob = _bh_first(do.astype(q.dtype))
    ob = _bh_first(o)
    # delta_i = sum_j do_ij o_ij;  ds = p * (dp + dlse - delta) * scale
    delta = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32),
                    axis=-1)  # [BH, Tq]
    # dlse arrives [B, H, Tq], which is (B*H, Tq)-contiguous already
    corr = (dlse.reshape(b * h, tq).astype(jnp.float32) - delta
            if dlse is not None else -delta)
    corr = corr.reshape(b * h, 1, tq)  # full-row blocks, like lse
    qb, kb, vb = _bh_first(q), _bh_first(k), _bh_first(v)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=block_q, block_k=block_k,
                          causal=causal, sm_scale=sm_scale, kv_len=tk),
        grid=(b * h, tq // block_q),
        in_specs=[
            _scalar_spec(), _scalar_spec(),
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, tk, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, tk, dv), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, block_q, dv), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, 1, tq), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, 1, tq), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        interpret=interpret,
    )(q_off, k_off, qb, kb, vb, dob, lse, corr)

    dk, dvv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
                          causal=causal, sm_scale=sm_scale, q_len=tq),
        grid=(b * h, tk // block_k),
        in_specs=[
            _scalar_spec(), _scalar_spec(),
            pl.BlockSpec((1, tq, d), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, dv), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, tq, dv), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, 1, tq), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, 1, tq), lambda bh, j: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, dv), lambda bh, j: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, dv), v.dtype),
        ],
        interpret=interpret,
    )(q_off, k_off, qb, kb, vb, dob, lse, corr)

    def back(x, t):  # [BH, T, D] -> [B, T, H, D]
        return x.reshape(b, h, t, x.shape[-1]).transpose(0, 2, 1, 3)

    return (back(dq, tq), back(dk, tk), back(dvv, tk),
            jnp.zeros_like(q_off), jnp.zeros_like(k_off))


_flash.defvjp(_flash_fwd_vjp, _flash_bwd)


def _pick_block(t: int, preferred: int) -> int:
    b = min(preferred, t)
    while t % b:
        b -= 1  # powers of two hit immediately
    if b < min(128, preferred, t):
        # a degenerate auto-shrunk divisor (prime/odd-factor T) would
        # compile into a pathologically fine-grained grid; fail loudly.
        # Explicitly requested small blocks (preferred <= b) stay allowed.
        raise ValueError(
            f"sequence length {t} has no block divisor >= 128; pad the "
            f"sequence (largest divisor found: {b})")
    return b


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: Optional[bool] = None,
                    q_offset=None, k_offset=None,
                    return_lse: bool = False):
    """softmax(QK^T)V without materializing the score matrix.

    q: [B, Tq, H, D]; k/v: [B, Tk, H, D(v)]. Block sizes shrink to divisors
    of the sequence lengths automatically (static shapes are the XLA
    contract anyway). ``q_offset``/``k_offset`` are global sequence
    positions of element 0 (traced scalars allowed) for causal masking of
    sequence-sharded blocks. ``return_lse=True`` also returns the per-row
    log-sum-exp, shaped [B, H, Tq], for online-softmax merging; both
    outputs are differentiable. ``interpret=None`` auto-selects interpret
    mode off-TPU so the same call runs in CPU tests.
    """
    b, tq, h, d = q.shape
    scale = sm_scale if sm_scale is not None else d ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q = _pick_block(tq, block_q)
    block_k = _pick_block(k.shape[1], block_k)
    q_off = (jnp.zeros((1,), jnp.float32) if q_offset is None
             else jnp.asarray(q_offset, jnp.float32).reshape(1))
    k_off = (jnp.zeros((1,), jnp.float32) if k_offset is None
             else jnp.asarray(k_offset, jnp.float32).reshape(1))
    o, lse = _flash(q, k, v, q_off, k_off, causal, scale, block_q, block_k,
                    interpret)
    return (o, lse) if return_lse else o


def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = False,
                  sm_scale: Optional[float] = None) -> jax.Array:
    """Plain XLA dot attention — the short-sequence winner.

    Same [B, T, H, D] layout and numerics contract as
    :func:`flash_attention` (matmuls in the input dtype, fp32 softmax), so
    the router can swap between them freely. At short T the [T, T] score
    matrix is small enough that XLA's fused softmax beats the Pallas
    kernel's grid setup cost.
    """
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else d ** -0.5
    # Matmuls stay in the input dtype (bf16 rides the fast MXU path, same
    # as the flash kernel) with fp32 accumulation; only the softmax runs
    # in fp32. Upcasting the operands would cost ~4x MXU throughput and 2x
    # HBM traffic on the [B, H, T, T] scores — the short-seq regime this
    # path exists to win.
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        if tq != tk:
            raise ValueError(
                "xla_attention supports causal only for self-attention "
                f"(Tq == Tk), got {tq} vs {tk}; use flash_attention with "
                "q_offset/k_offset for sharded causal blocks")
        mask = jnp.tril(jnp.ones((tq, tk), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def flash_min_seq() -> int:
    """The routing crossover (elements of Tk), env-overridable."""
    from horovod_tpu.common.env_registry import env_int
    return env_int("HOROVOD_FLASH_MIN_SEQ", DEFAULT_FLASH_MIN_SEQ)


def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              causal: bool = False,
              sm_scale: Optional[float] = None,
              min_flash_seq: Optional[int] = None,
              **flash_kwargs) -> jax.Array:
    """Length-routed attention: XLA dot attention below the measured
    crossover, the Pallas flash kernel at/above it.

    BENCH_r05 showed ``use_flash=True`` costing 16% at seq 128 — a kernel
    built for long context has nothing to amortize on tiny score tiles.
    This router keeps the long-context win (3x+ at 8k causal) without
    making short-sequence models pay for it. Routing keys on the KV length
    (the side that grows the score matrix). Semantics-bearing flash-only
    features (``return_lse``, ``q_offset``/``k_offset``) force the flash
    path regardless of length — the XLA path cannot honor them, and
    silently dropping them would change the return contract or the causal
    mask (ring attention relies on exactly these).
    """
    if flash_kwargs.get("return_lse") or \
            flash_kwargs.get("q_offset") is not None or \
            flash_kwargs.get("k_offset") is not None:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               **flash_kwargs)
    threshold = min_flash_seq if min_flash_seq is not None else \
        flash_min_seq()
    if k.shape[1] < threshold:
        # flash_kwargs here can only hold tuning knobs (block sizes /
        # interpret), which have no meaning for the XLA formulation.
        return xla_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                           **flash_kwargs)


def merge_attention(o_a: jax.Array, lse_a: jax.Array,
                    o_b: jax.Array, lse_b: jax.Array
                    ) -> Tuple[jax.Array, jax.Array]:
    """Exactly merge two attention partials (normalized outputs + lse) over
    disjoint key sets — the online-softmax combine ring attention runs per
    step. o: [B, T, H, Dv], lse: [B, H, T]."""
    m = jnp.maximum(lse_a, lse_b)
    m_safe = jnp.where(m > NEG_INF / 2, m, 0.0)
    wa = jnp.exp(lse_a - m_safe)
    wb = jnp.exp(lse_b - m_safe)
    denom = jnp.maximum(wa + wb, 1e-30)
    # weights arrive [B, H, T]; outputs are [B, T, H, Dv]
    fa = (wa / denom).transpose(0, 2, 1)[..., None]
    fb = (wb / denom).transpose(0, 2, 1)[..., None]
    o = o_a.astype(jnp.float32) * fa + o_b.astype(jnp.float32) * fb
    lse = jnp.where(m > NEG_INF / 2, m + jnp.log(denom), NEG_INF)
    return o.astype(o_a.dtype), lse
