"""Fused flash attention as a Pallas TPU kernel.

The hot op of the transformer path (BASELINE config 3): computes
softmax(QK^T)V blockwise in VMEM with online log-sum-exp accumulation, so
the [T, T] score matrix never exists in HBM — the kernel streams K/V blocks
through the MXU and keeps the fp32 accumulators on chip. This is the
single-device building block sequence parallelism composes with
(parallel/sp.py shards the sequence across chips; this kernel is the
within-shard engine).

Layout: [batch, seq, heads, head_dim] in, same out. Internally each
(batch, head) pair is one grid row — batch*heads independent programs —
and the q dimension tiles over the grid's second axis.

Pure-JAX reference semantics are tested against in interpret mode (CPU)
and the kernel compile-checks on the real chip.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() NaN-free


def _kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
            sm_scale: float, block_q: int, seq_len: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [block_q, d]

    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    o = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(kj, carry):
        m, l, o = carry
        k = k_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [block_q, block_k]
        if causal:
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_i = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_i)
        p = jnp.exp(s - m_new)  # rows fully at NEG_INF decay to ~0
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o = o * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, o

    num_k = seq_len // block_k
    if causal:
        # blocks entirely in this q-tile's future contribute nothing;
        # bound the loop instead of masking them
        num_k = jnp.minimum(num_k,
                            (qi + 1) * block_q // block_k +
                            (1 if block_q % block_k else 0))
    m, l, o = jax.lax.fori_loop(0, num_k, body, (m, l, o))
    o_ref[0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool = False) -> jax.Array:
    """softmax(QK^T)V without materializing the score matrix.

    q/k/v: [B, T, H, D]; T must divide by the block sizes (pad upstream —
    static shapes are the XLA contract anyway)."""
    b, t, h, d = q.shape
    if t % block_q or t % block_k:
        raise ValueError(f"seq len {t} must divide block sizes "
                         f"({block_q}, {block_k})")
    scale = sm_scale if sm_scale is not None else d ** -0.5

    def bh_first(x):  # [B, T, H, D] -> [B*H, T, D]
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, x.shape[-1])

    qb, kb, vb = bh_first(q), bh_first(k), bh_first(v)
    grid = (b * h, t // block_q)
    kernel = functools.partial(_kernel, block_k=block_k, causal=causal,
                               sm_scale=scale, block_q=block_q, seq_len=t)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, t, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, t, v.shape[-1]), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, v.shape[-1]),
                               lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, v.shape[-1]), q.dtype),
        interpret=interpret,
    )(qb, kb, vb)
    return out.reshape(b, h, t, v.shape[-1]).transpose(0, 2, 1, 3)
