"""In-program tensor fusion: many small collectives → one big one.

The reference's fusion buffer memcpys small tensors into a persistent 128 MB
device buffer, runs one collective, and unpacks
(reference: horovod/common/fusion_buffer_manager.cc,
ops/collective_operations.h:65-86, threshold set at operations.cc:444).

Under XLA the packing is free to express — we concatenate flattened tensors
per dtype inside the traced program and let the compiler schedule the copies —
and the payoff is identical: one ICI collective instead of N, amortizing
per-collective latency for the long tail of small gradients.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp


def fused_apply(fn: Callable[[jax.Array], jax.Array],
                xs: Sequence[jax.Array]) -> List[jax.Array]:
    """Apply an elementwise-collective ``fn`` to all of ``xs`` fused per dtype.

    ``fn`` must be shape-preserving and elementwise-independent (allreduce
    variants are; allgather/alltoall are not — those fuse at the engine level
    instead)."""
    xs = list(xs)
    if not xs:
        return []
    if len(xs) == 1:
        return [fn(xs[0])]

    # Stable grouping by dtype, mirroring the reference's per-(device,dtype)
    # fusion constraint (controller.cc FuseResponses requires matching types).
    groups: dict = {}
    for i, x in enumerate(xs):
        groups.setdefault(jnp.dtype(x.dtype), []).append(i)

    out: List = [None] * len(xs)
    for dtype, idxs in groups.items():
        if len(idxs) == 1:
            i = idxs[0]
            out[i] = fn(xs[i])
            continue
        flat = [xs[i].ravel() for i in idxs]
        sizes = [f.size for f in flat]
        fused = jnp.concatenate(flat)
        reduced = fn(fused)
        offset = 0
        for i, sz in zip(idxs, sizes):
            out[i] = reduced[offset:offset + sz].reshape(xs[i].shape)
            offset += sz
    return out


def fused_apply_tree(fn: Callable[[jax.Array], jax.Array], tree):
    """Tree-structured variant: fuse every leaf of a pytree (a grads pytree),
    preserving structure — the DistributedOptimizer hot path."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return jax.tree_util.tree_unflatten(treedef, fused_apply(fn, leaves))
