"""TensorFlow eager/graph collective ops over the native coordination engine.

Reference analog: horovod/tensorflow/mpi_ops.py (op wrappers + gradient
registration, :95-320) and horovod/tensorflow/mpi_ops.cc (the C++ kernels).

TPU-native design: like torch, TensorFlow is a *frontend* over the
framework-neutral eager layer (horovod_tpu/common/eager.py) — tensors stage
to host numpy, the C++ engine negotiates/fuses across ranks, the host data
plane executes. Instead of registering graph-op gradients with
``ops.RegisterGradient`` against custom kernels, each op is a
``tf.custom_gradient`` around a ``tf.py_function``, which makes it
differentiable and usable from both eager code and ``tf.function`` graphs
with zero native TF code. The TPU compute path stays in jit
(horovod_tpu.jax); this surface serves tf training loops and API parity.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import tensorflow as tf

from horovod_tpu.common import basics
from horovod_tpu.common import eager as _eager
from horovod_tpu.common.reduce_ops import (  # noqa: F401  (re-exported)
    Adasum, Average, Max, Min, Op, Product, Sum,
)

# re-exported context surface (reference: mpi_ops.py init/rank/size exports)
init = basics.init
shutdown = basics.shutdown
is_initialized = basics.is_initialized
rank = basics.rank
size = basics.size
local_rank = basics.local_rank
local_size = basics.local_size
cross_rank = basics.cross_rank
cross_size = basics.cross_size


def _np(t: tf.Tensor) -> np.ndarray:
    # tf numpy interop preserves dtype incl. bfloat16 (ml_dtypes-backed)
    return np.asarray(t.numpy())


def _scalar_normalize(out: tf.Tensor, like: tf.Tensor) -> tf.Tensor:
    return tf.ensure_shape(out, like.shape) if like.shape.is_fully_defined() \
        else out


def allreduce(tensor, average=None, name: Optional[str] = None, op=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              compression=None):
    """Differentiable allreduce (reference: tensorflow/__init__.py:54-155 +
    mpi_ops.py:95-134; gradient = the mirror allreduce)."""
    from horovod_tpu.tensorflow.compression import Compression
    compression = compression or Compression.none
    red_op = _eager.resolve_op(op, average)
    tensor = tf.convert_to_tensor(tensor)
    compressed, ctx = compression.compress(tensor)

    @tf.custom_gradient
    def _fn(t):
        def _run(x):
            return _eager.synchronize(_eager.allreduce_async(
                _np(x), name=name, op=red_op,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor))
        out = tf.py_function(_run, [t], t.dtype)
        out = _scalar_normalize(out, t)

        def grad(dy):
            return allreduce(dy, op=red_op,
                             prescale_factor=prescale_factor,
                             postscale_factor=postscale_factor)
        return out, grad

    return compression.decompress(_fn(compressed), ctx)


def grouped_allreduce(tensors, average=None, name: Optional[str] = None,
                      op=None, prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0, compression=None):
    """One negotiation round for a list of tensors (reference:
    tensorflow/__init__.py:156-232). Grouped entries fuse unconditionally in
    the engine."""
    from horovod_tpu.tensorflow.compression import Compression
    compression = compression or Compression.none
    red_op = _eager.resolve_op(op, average)
    tensors = [tf.convert_to_tensor(t) for t in tensors]
    comp = [compression.compress(t) for t in tensors]

    @tf.custom_gradient
    def _fn(*ts):
        def _run(*xs):
            hs = _eager.grouped_allreduce_async(
                [_np(x) for x in xs], name=name, op=red_op,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor)
            return [_eager.synchronize(h) for h in hs]
        outs = tf.py_function(_run, list(ts), [t.dtype for t in ts])
        outs = [_scalar_normalize(o, t) for o, t in zip(outs, ts)]

        def grad(*dys):
            return grouped_allreduce(list(dys), op=red_op,
                                     prescale_factor=prescale_factor,
                                     postscale_factor=postscale_factor)
        return outs, grad

    reduced = _fn(*[c for c, _ in comp])
    return [compression.decompress(r, ctx)
            for r, (_, ctx) in zip(reduced, comp)]


def allgather(tensor, name: Optional[str] = None):
    """Differentiable allgather along dim 0; ranks may contribute different
    row counts (reference: mpi_ops.py:184-230; gradient = allreduce-sum +
    slice of this rank's rows, using the per-rank sizes that ride the
    handle's aux channel — no second collective)."""
    tensor = tf.convert_to_tensor(tensor)

    @tf.custom_gradient
    def _fn(t):
        def _run(x):
            h = _eager.allgather_async(_np(x), name=name)
            out = _eager.synchronize(h)
            sizes = h.aux.get("rank_sizes")
            if sizes is None:
                sizes = np.asarray([out.shape[0] if out.ndim else 1])
            return out, np.asarray(sizes, np.int64)
        out, sizes = tf.py_function(_run, [t], [t.dtype, tf.int64])

        def grad(dy):
            g = allreduce(dy, op=Sum)
            r = basics._context().rank
            off = tf.reduce_sum(sizes[:r])
            return g[off:off + sizes[r]]
        return out, grad

    return _fn(tensor)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Differentiable broadcast (reference: mpi_ops.py:231-267; gradient
    reduces to the root, zeros elsewhere)."""
    tensor = tf.convert_to_tensor(tensor)

    @tf.custom_gradient
    def _fn(t):
        def _run(x):
            return _eager.synchronize(_eager.broadcast_async(
                _np(x), root_rank, name=name))
        out = tf.py_function(_run, [t], t.dtype)
        out = _scalar_normalize(out, t)

        def grad(dy):
            g = allreduce(dy, op=Sum)
            if basics._context().rank != root_rank:
                g = tf.zeros_like(g)
            return g
        return out, grad

    return _fn(tensor)


def alltoall(tensor, splits=None, name: Optional[str] = None):
    """Differentiable alltoall (reference: mpi_ops.py:268-322; gradient =
    alltoall back along the received splits)."""
    tensor = tf.convert_to_tensor(tensor)
    if splits is not None and isinstance(splits, tf.Tensor):
        # symbolic under tf.function — feed through the dynamic variant,
        # which passes splits as a py_function input instead of
        # materializing them at trace time
        return _alltoall_dynamic(tensor, tf.cast(splits, tf.int64),
                                 name=name)

    @tf.custom_gradient
    def _fn(t):
        def _run(x):
            h = _eager.alltoall_async(_np(x), splits=splits, name=name)
            out = _eager.synchronize(h)
            recv = h.aux.get("recv_splits")
            if recv is None:
                recv = [out.shape[0] if out.ndim else 1]
            return out, np.asarray(recv, np.int64)
        # recv splits ride a tensor output (not a python side-channel) so
        # the gradient is correct under tf.function, where the grad fn is
        # traced before the forward py_function ever runs
        out, recv_splits = tf.py_function(_run, [t], [t.dtype, tf.int64])

        def grad(dy):
            return _alltoall_dynamic(dy, recv_splits)
        return out, grad

    return _fn(tensor)


def _alltoall_dynamic(tensor, splits_t, name: Optional[str] = None):
    """alltoall whose splits arrive as a tensor (the symbolic-splits and
    backward paths)."""
    @tf.custom_gradient
    def _fn(t, s):
        def _run(x, sp):
            h = _eager.alltoall_async(
                _np(x), splits=[int(v) for v in np.asarray(sp)], name=name)
            out = _eager.synchronize(h)
            recv = h.aux.get("recv_splits")
            if recv is None:
                recv = [out.shape[0] if out.ndim else 1]
            return out, np.asarray(recv, np.int64)
        out, recv = tf.py_function(_run, [t, s], [t.dtype, tf.int64])

        def grad(dy, *_unused):
            return _alltoall_dynamic(dy, recv), None
        return (out, recv), grad

    return _fn(tensor, splits_t)[0]


def join() -> int:
    """Block until every rank joins; returns the last joined rank
    (reference: mpi_ops.py:323-326)."""
    return _eager.join()


def barrier():
    _eager.barrier()


# -- graph-friendly topology ops (reference: mpi_ops.py:327-392 size_op etc.;
# here topology is static per generation, so constants suffice) -------------


def size_op(name: Optional[str] = None):
    return tf.constant(basics.size(), tf.int32, name=name)


def local_size_op(name: Optional[str] = None):
    return tf.constant(basics.local_size(), tf.int32, name=name)


def rank_op(name: Optional[str] = None):
    return tf.constant(basics.rank(), tf.int32, name=name)


def local_rank_op(name: Optional[str] = None):
    return tf.constant(basics.local_rank(), tf.int32, name=name)
