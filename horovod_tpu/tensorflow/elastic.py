"""Elastic training state for TensorFlow/Keras.

Reference analog: horovod/tensorflow/elastic.py — TensorFlowKerasState
(:91-155, keras model + optimizer handlers) and the shared retry loop. The
run() wrapper and commit/restore/interrupt machinery are framework-neutral
and come from horovod_tpu.jax.elastic.
"""

from __future__ import annotations

from typing import Optional

from horovod_tpu.common import basics
from horovod_tpu.jax.elastic import (  # noqa: F401  (re-exported)
    HostsUpdatedInterrupt, State, run,
)


class TensorFlowKerasState(State):
    """Elastic state wrapping a keras model (+ optimizer): commit snapshots
    weights host-side, restore reloads them, sync broadcasts variables from
    rank 0 (reference: tensorflow/elastic.py:91-155)."""

    def __init__(self, model=None, optimizer=None, **kwargs):
        self.model = model
        self.optimizer = optimizer if optimizer is not None else \
            getattr(model, "optimizer", None)
        self._model_weights = None
        self._optimizer_weights = None
        super().__init__(**kwargs)

    def _opt_vars(self):
        if self.optimizer is None:
            return []
        v = getattr(self.optimizer, "variables", [])
        return v() if callable(v) else list(v)

    def commit_no_check(self):
        if self.model is not None:
            self._model_weights = [w.copy() for w in self.model.get_weights()]
        self._optimizer_weights = [v.numpy().copy() for v in self._opt_vars()]
        super().commit_no_check()

    def restore(self):
        if self.model is not None and self._model_weights is not None:
            self.model.set_weights(self._model_weights)
        if self._optimizer_weights:
            for var, w in zip(self._opt_vars(), self._optimizer_weights):
                var.assign(w)
        super().restore()

    def sync(self):
        if not basics._single_process():
            from horovod_tpu.tensorflow.functions import broadcast_variables
            if self.model is not None:
                broadcast_variables(self.model.variables, 0)
            opt_vars = self._opt_vars()
            if opt_vars:
                broadcast_variables(opt_vars, 0)
        super().sync()


# alias for parity with the pure-tf state of the reference (variables are
# keras-managed in tf2; the keras state covers both)
TensorFlowState = TensorFlowKerasState
