"""Keras sub-frontend (reference: horovod/tensorflow/keras/__init__.py).

Re-exports the tensorflow surface plus the keras callbacks; the
DistributedOptimizer here is the keras-flavored one (same implementation —
the tf frontend already targets keras-3 optimizers).
"""

from horovod_tpu.tensorflow import (  # noqa: F401
    Adasum, Average, Compression, Max, Min, Op, Product, Sum,
    DistributedOptimizer, DistributedGradientTape,
    allgather, allgather_object, allreduce, alltoall, barrier, broadcast,
    broadcast_model, broadcast_object, broadcast_variables,
    grouped_allreduce, init, is_initialized, join, local_rank, local_size,
    metric_average, rank, shutdown, size,
)
from horovod_tpu.keras import callbacks  # noqa: F401
from horovod_tpu.keras import load_model  # noqa: F401
