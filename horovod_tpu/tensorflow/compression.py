"""Gradient compression for the TensorFlow frontend.

Reference analog: horovod/tensorflow/compression.py (NoneCompressor /
FP16Compressor selected via the ``Compression`` enum-class). Adds a bf16
compressor — the TPU-native 16-bit format with fp32 range.
"""

from __future__ import annotations

import tensorflow as tf


class Compressor:
    """Interface: compress before allreduce, decompress after."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context) — context feeds decompress."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating:
            return tf.cast(tensor, tf.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tf.cast(tensor, ctx) if ctx is not None else tensor


class BF16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating:
            return tf.cast(tensor, tf.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tf.cast(tensor, ctx) if ctx is not None else tensor


class Compression:
    """Pick a compressor by attribute (reference: compression.py Compression).
    """
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
