"""Variable/object broadcast helpers for the TensorFlow frontend.

Reference analog: horovod/tensorflow/functions.py — broadcast_variables
(:47-58), broadcast_object (:59-102), allgather_object (:136-161).

Object transport is framework-neutral (pickle + numpy over the engine), so
it delegates to the jax frontend's implementations, which operate purely on
numpy buffers.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

import numpy as np
import tensorflow as tf

from horovod_tpu.common import eager as _eager
from horovod_tpu.jax.functions import (  # noqa: F401  (re-exported)
    allgather_object, broadcast_object,
)


def broadcast_variables(variables: Iterable[tf.Variable], root_rank: int = 0):
    """Assign every variable its root-rank value (reference:
    functions.py:47-58 — the post-init consistency sync).

    Async-submits every leaf then synchronizes, letting the engine pipeline
    and fuse the transfers.
    """
    variables = list(variables)
    handles = [_eager.broadcast_async(np.asarray(v.numpy()), root_rank,
                                      name=f"bcast_vars.{i}")
               for i, v in enumerate(variables)]
    for v, h in zip(variables, handles):
        out = tf.cast(_eager.synchronize(h), v.dtype)
        # the engine normalizes 0-d scalars to rank-1; restore the shape
        v.assign(tf.reshape(out, v.shape))


def broadcast_model(model, root_rank: int = 0, optimizer=None):
    """Broadcast a keras model's (and optionally optimizer's) variables
    (reference: the BroadcastGlobalVariablesCallback body,
    _keras/callbacks.py:22-47)."""
    broadcast_variables(model.variables, root_rank)
    if optimizer is not None and getattr(optimizer, "variables", None):
        opt_vars = optimizer.variables
        opt_vars = opt_vars() if callable(opt_vars) else opt_vars
        broadcast_variables(opt_vars, root_rank)
