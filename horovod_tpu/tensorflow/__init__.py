"""TensorFlow frontend: Horovod-parity API over the TPU-native engine.

Reference analog: horovod/tensorflow/__init__.py — the op surface
(allreduce/allgather/broadcast/alltoall, :54-330), DistributedOptimizer
(:568-670), DistributedGradientTape (:674-742) — rebuilt over the
framework-neutral eager layer instead of per-framework C++ kernels.

Usage mirrors the reference::

    import horovod_tpu.tensorflow as hvd
    hvd.init()
    tape = hvd.DistributedGradientTape(tape)
    # or
    opt = hvd.DistributedOptimizer(opt)
    hvd.broadcast_variables(model.variables, root_rank=0)
"""

from __future__ import annotations

from typing import Optional

import tensorflow as tf

from horovod_tpu.common.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, mesh, num_replicas, is_homogeneous,
    mpi_threads_supported, mpi_enabled, mpi_built, gloo_enabled, gloo_built,
    nccl_built, ddl_built, ccl_built, cuda_built, rocm_built,
    start_timeline, stop_timeline,
)
from horovod_tpu.common.exceptions import (  # noqa: F401
    HorovodInternalError, HostsUpdatedInterrupt,
)
from horovod_tpu.tensorflow.compression import Compression  # noqa: F401
from horovod_tpu.tensorflow.functions import (  # noqa: F401
    allgather_object, broadcast_object, broadcast_model, broadcast_variables,
)
from horovod_tpu.tensorflow.sync_batch_norm import (  # noqa: F401
    SyncBatchNormalization,
)
from horovod_tpu.tensorflow.mpi_ops import (  # noqa: F401
    Adasum, Average, Max, Min, Op, Product, Sum,
    allgather, allreduce, alltoall, barrier, broadcast, grouped_allreduce,
    join, local_rank_op, local_size_op, rank_op, size_op,
)


def _allreduce_sparse(slices: tf.IndexedSlices, op, name=None):
    """Sparse "allreduce": allgather every rank's (values, indices) slabs
    (reference: tensorflow/__init__.py:92-108 — sparse gradients ride
    allgather; Average divides the gathered values by the world size).
    Duplicate indices are fine — downstream scatter-add semantics sum
    them, exactly like a dense sum would."""
    if op not in (Average, Sum):
        raise NotImplementedError(
            "sparse allreduce supports Sum/Average only")
    values = allgather(slices.values, name=f"{name}.values" if name else None)
    indices = allgather(slices.indices,
                        name=f"{name}.indices" if name else None)
    if op == Average:
        values = values / tf.cast(size_op(), values.dtype)
    return tf.IndexedSlices(values=values, indices=indices,
                            dense_shape=slices.dense_shape)


def _make_allreduce_grads_fn(compression, op, gradient_predivide_factor,
                             num_groups, sparse_as_dense=False):
    """Gradient-combining closure shared by the tape and optimizer wrappers
    (reference: tensorflow/__init__.py:334-418 _make_allreduce_grads_fn +
    _make_cached_allreduce_grads_fn). ``tf.IndexedSlices`` gradients take
    the allgather path (or densify with sparse_as_dense)."""
    if gradient_predivide_factor != 1.0 and op != Average:
        raise ValueError(
            "gradient_predivide_factor not supported with op != Average")

    def _allreduce_grads(grads):
        prescale = postscale = 1.0
        red_op = op
        if gradient_predivide_factor != 1.0:
            # split the averaging around the sum (reference:
            # __init__.py:118-125); size() is read per call so pre-init
            # construction and elastic resizes can't bake in a stale world
            prescale = 1.0 / gradient_predivide_factor
            postscale = gradient_predivide_factor / size()
            red_op = Sum
        grads = list(grads)
        sparse_idx = []
        for i, g in enumerate(grads):
            if isinstance(g, tf.IndexedSlices):
                if sparse_as_dense:
                    grads[i] = tf.convert_to_tensor(g)
                else:
                    sparse_idx.append(i)
        idx = [i for i, g in enumerate(grads)
               if g is not None and i not in sparse_idx]
        dense = [tf.convert_to_tensor(grads[i]) for i in idx]
        out = list(grads)
        for i in sparse_idx:
            out[i] = _allreduce_sparse(grads[i], op=op)
        if not dense:
            return out
        if num_groups > 0:
            reduced = []
            n = max(1, (len(dense) + num_groups - 1) // num_groups)
            for s in range(0, len(dense), n):
                reduced.extend(grouped_allreduce(
                    dense[s:s + n], op=red_op, compression=compression,
                    prescale_factor=prescale, postscale_factor=postscale))
        else:
            reduced = grouped_allreduce(
                dense, op=red_op, compression=compression,
                prescale_factor=prescale, postscale_factor=postscale)
        for i, r in zip(idx, reduced):
            out[i] = r
        return out

    return _allreduce_grads


def _class_body(mixin) -> dict:
    """A mixin's methods, minus the instance-layout descriptors a standalone
    class carries (they don't transplant onto a dynamic subclass)."""
    return {k: v for k, v in mixin.__dict__.items()
            if k not in ("__dict__", "__weakref__")}


class _DistributedOptimizer:
    """Methods grafted onto a dynamic subclass of the wrapped keras
    optimizer's class (reference: _keras/__init__.py:24-137 — the same
    type()-composition trick, so isinstance checks and get_config
    round-trips keep working). The parent class rides the state dict
    rather than ``super(self.__class__, ...)`` — the latter recurses
    forever if anything subclasses the dynamic class again."""

    _HVD_ATTR = "_hvd_state"

    def apply_gradients(self, grads_and_vars, *args, **kwargs):
        st = getattr(self, self._HVD_ATTR)
        base = st["base_class"]
        pairs = [(g, v) for g, v in grads_and_vars]
        grads = [g for g, _ in pairs]
        varss = [v for _, v in pairs]
        helper = st["aggregation_helper"]
        if helper is not None:
            # graph-safe local aggregation: tf.Variable accumulators +
            # tf.cond, usable inside tf.function (reference:
            # gradient_aggregation.py LocalGradientAggregationHelper)
            if hasattr(self, "built") and not self.built:
                # slot variables must exist before the cond branches —
                # creating them inside tf.cond is illegal under tf.function
                self.build(varss)
            # compute_gradients allreduces on boundary calls itself; the
            # cond in helper.apply_gradients gates the real apply
            grads = helper.compute_gradients(grads)

            def _apply():
                return base.apply_gradients(
                    self, list(zip(grads, varss)), *args, **kwargs)

            return helper.apply_gradients(_apply, self)
        reduced = st["allreduce_grads"](grads)
        return base.apply_gradients(self, list(zip(reduced, varss)),
                                    *args, **kwargs)


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         use_locking: bool = False, device_dense: str = "",
                         device_sparse: str = "",
                         compression=Compression.none,
                         sparse_as_dense: bool = False,
                         backward_passes_per_step: int = 1,
                         op=Average, gradient_predivide_factor: float = 1.0,
                         average_aggregated_gradients: bool = False,
                         num_groups: int = 0):
    """Wrap a keras optimizer so apply_gradients combines gradients across
    ranks first (reference: tensorflow/__init__.py:568-670). device_dense /
    device_sparse / use_locking are accepted for API parity; placement is
    the engine's concern here. IndexedSlices gradients ride the sparse
    allgather path unless ``sparse_as_dense`` densifies them."""
    if op == Adasum and average_aggregated_gradients:
        raise ValueError(
            "Adasum does not support average_aggregated_gradients")
    if hasattr(optimizer, _DistributedOptimizer._HVD_ATTR):
        raise ValueError(
            "optimizer is already a DistributedOptimizer; wrapping it "
            "twice would allreduce twice")
    _ = (name, use_locking, device_dense, device_sparse)
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               _class_body(_DistributedOptimizer))
    opt = cls.from_config(optimizer.get_config())
    allreduce_grads = _make_allreduce_grads_fn(
        compression, op, gradient_predivide_factor, num_groups,
        sparse_as_dense=sparse_as_dense)
    helper = None
    if backward_passes_per_step > 1:
        from horovod_tpu.tensorflow.gradient_aggregation import \
            LocalGradientAggregationHelper
        helper = LocalGradientAggregationHelper(
            backward_passes_per_step, allreduce_grads,
            sparse_as_dense=sparse_as_dense,
            average_aggregated_gradients=average_aggregated_gradients)
    setattr(opt, _DistributedOptimizer._HVD_ATTR, {
        "allreduce_grads": allreduce_grads,
        "aggregation_helper": helper,
        "base_class": optimizer.__class__,
    })
    return opt


class _DistributedGradientTape:
    def gradient(self, target, sources, output_gradients=None):
        grads = self._hvd_base_class.gradient(self, target, sources,
                                              output_gradients)
        one = not isinstance(grads, (list, tuple))
        reduced = self._hvd_allreduce_grads([grads] if one else list(grads))
        return reduced[0] if one else reduced


def DistributedGradientTape(gradtape: tf.GradientTape, device_dense: str = "",
                            device_sparse: str = "",
                            compression=Compression.none,
                            sparse_as_dense: bool = False, op=Average,
                            gradient_predivide_factor: float = 1.0,
                            num_groups: int = 0):
    """Wrap a tf.GradientTape so .gradient() returns rank-combined gradients
    (reference: tensorflow/__init__.py:674-742, same dynamic-subclass
    shape)."""
    _ = (device_dense, device_sparse)
    if hasattr(gradtape, "_hvd_base_class"):
        raise ValueError(
            "tape is already a DistributedGradientTape; wrapping it twice "
            "would allreduce twice")
    cls = type(gradtape.__class__.__name__, (gradtape.__class__,),
               _class_body(_DistributedGradientTape))
    tape = cls.__new__(cls)
    tape.__dict__.update(gradtape.__dict__)
    tape._hvd_base_class = gradtape.__class__
    tape._hvd_allreduce_grads = _make_allreduce_grads_fn(
        compression, op, gradient_predivide_factor, num_groups,
        sparse_as_dense=sparse_as_dense)
    return tape


def metric_average(value, name: Optional[str] = None):
    """Average a python/tf scalar across ranks (used by the keras
    MetricAverageCallback; reference: _keras/callbacks.py:48-88)."""
    import numpy as np
    out = allreduce(tf.convert_to_tensor(np.asarray(value, np.float32)),
                    op=Average, name=name)
    return float(out.numpy())
