"""Cross-rank synchronized batch normalization for TensorFlow/Keras.

Reference analog: horovod/tensorflow/sync_batch_norm.py
(SyncBatchNormalization overriding _moments with allreduced statistics).

Design (same as the torch frontend's sync BN): statistics are computed with
the *differentiable* allreduce, so autograd produces exactly the
synchronized gradients — no hand-derived backward. Implemented as a
standalone keras-3 layer because keras-3's BatchNormalization no longer
exposes a _moments hook.
"""

from __future__ import annotations

import keras
import tensorflow as tf

from horovod_tpu.common import basics
from horovod_tpu.tensorflow import mpi_ops


class SyncBatchNormalization(keras.layers.Layer):
    """BatchNormalization whose batch statistics are computed over the
    global batch (all ranks), for when per-rank batches are too small for
    stable BN. Channels-last (axis=-1)."""

    def __init__(self, axis: int = -1, momentum: float = 0.99,
                 epsilon: float = 1e-3, center: bool = True,
                 scale: bool = True, **kwargs):
        super().__init__(**kwargs)
        if axis != -1:
            raise ValueError("SyncBatchNormalization supports axis=-1 "
                             "(channels-last) only")
        self.momentum = momentum
        self.epsilon = epsilon
        self.center = center
        self.scale = scale

    def build(self, input_shape):
        ch = int(input_shape[-1])
        if self.scale:
            self.gamma = self.add_weight(name="gamma", shape=(ch,),
                                         initializer="ones", trainable=True)
        if self.center:
            self.beta = self.add_weight(name="beta", shape=(ch,),
                                        initializer="zeros", trainable=True)
        self.moving_mean = self.add_weight(
            name="moving_mean", shape=(ch,), initializer="zeros",
            trainable=False)
        self.moving_variance = self.add_weight(
            name="moving_variance", shape=(ch,), initializer="ones",
            trainable=False)
        super().build(input_shape)

    def call(self, inputs, training=False):
        ctx = basics._context()
        world = ctx.size if ctx.initialized else 1
        if not training:
            mean, var = self.moving_mean, self.moving_variance
        else:
            axes = list(range(inputs.shape.rank - 1))
            local_count = tf.cast(
                tf.reduce_prod(tf.shape(inputs)[:-1]), tf.float32)
            local_sum = tf.reduce_sum(inputs, axis=axes)
            local_sqsum = tf.reduce_sum(tf.square(inputs), axis=axes)
            if world > 1:
                total = mpi_ops.allreduce(
                    tf.reshape(local_count, (1,)), op=mpi_ops.Sum)[0]
                gsum = mpi_ops.allreduce(local_sum, op=mpi_ops.Sum)
                gsqsum = mpi_ops.allreduce(local_sqsum, op=mpi_ops.Sum)
            else:
                total, gsum, gsqsum = local_count, local_sum, local_sqsum
            mean = gsum / total
            var = gsqsum / total - tf.square(mean)
            m = self.momentum
            self.moving_mean.assign(self.moving_mean * m +
                                    tf.stop_gradient(mean) * (1 - m))
            self.moving_variance.assign(self.moving_variance * m +
                                        tf.stop_gradient(var) * (1 - m))
        out = (inputs - mean) * tf.math.rsqrt(var + self.epsilon)
        if self.scale:
            out = out * self.gamma
        if self.center:
            out = out + self.beta
        return out
