"""Graph-safe local gradient aggregation for TensorFlow.

Reference analog: horovod/tensorflow/gradient_aggregation.py:1-268
(LocalGradientAggregationHelper) — accumulate gradients into tf.Variables
and gate the allreduce + optimizer apply on every
``backward_passes_per_step``-th call with ``tf.cond``, so the entire
training step (including the skipped calls) stays traceable inside one
``tf.function``. Python-dict accumulation only works eagerly; variables +
cond work in both modes.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import tensorflow as tf


class LocalGradientAggregationHelper:
    """Accumulates gradients locally for ``backward_passes_per_step`` calls,
    then allreduces and hands the combined gradients to the optimizer.

    State lives in non-trainable tf.Variables created on the first
    ``compute_gradients`` call (trace time under tf.function — exactly when
    variable creation is permitted), so retraces reuse them.
    """

    def __init__(self, backward_passes_per_step: int,
                 allreduce_func: Callable[[list], list],
                 sparse_as_dense: bool = False,
                 average_aggregated_gradients: bool = False):
        if backward_passes_per_step < 1:
            raise ValueError("backward_passes_per_step must be >= 1, got "
                             f"{backward_passes_per_step}")
        self.backward_passes_per_step = backward_passes_per_step
        self._allreduce_grads = allreduce_func
        self._sparse_as_dense = sparse_as_dense
        self._average = average_aggregated_gradients
        self.counter: Optional[tf.Variable] = None
        self._agg: dict = {}  # grad index -> accumulator Variable
        self._none_idx: List[int] = []

    def _init_vars(self, grads):
        if self.counter is not None:
            return
        self.counter = tf.Variable(0, trainable=False, dtype=tf.int32,
                                   name="hvd_aggregation_counter")
        for i, g in enumerate(grads):
            if g is None:
                self._none_idx.append(i)
                continue
            self._agg[i] = tf.Variable(
                tf.zeros_like(g), trainable=False,
                name=f"hvd_locally_aggregated_grad_{i}")

    def compute_gradients(self, grads: list) -> list:
        """Accumulate this call's gradients; returns the allreduced
        aggregate on boundary calls and zeros otherwise (the paired
        ``apply_gradients`` cond skips the optimizer on the zeros)."""
        grads = list(grads)
        for i, g in enumerate(grads):
            if isinstance(g, tf.IndexedSlices):
                if not self._sparse_as_dense:
                    raise ValueError(
                        "IndexedSlices gradients cannot be locally "
                        "aggregated with backward_passes_per_step > 1; "
                        "pass sparse_as_dense=True (reference requires the "
                        "same, gradient_aggregation.py)")
                grads[i] = tf.convert_to_tensor(g)
        self._init_vars(grads)
        updates = [self._agg[i].assign_add(g) for i, g in enumerate(grads)
                   if g is not None]
        with tf.control_dependencies(updates):
            counter = self.counter.assign_add(1)

        def _boundary():
            acc = [self._agg[i].read_value() if i in self._agg else None
                   for i in range(len(grads))]
            if self._average:
                acc = [None if a is None else
                       a / float(self.backward_passes_per_step) for a in acc]
            reduced = self._allreduce_grads(acc)
            dense = [r for r in reduced if r is not None]
            # zero the accumulators only after the reduced values exist
            with tf.control_dependencies(dense):
                resets = [v.assign(tf.zeros_like(v))
                          for v in self._agg.values()]
                resets.append(self.counter.assign(0))
            with tf.control_dependencies(resets):
                return [None if r is None else tf.identity(r)
                        for r in reduced]

        def _skip():
            return [None if g is None else tf.zeros_like(g) for g in grads]

        # tf.cond branches must return matching tensor structures; None
        # slots are identical in both, so carry only the tensors through
        none_idx = set(self._none_idx)

        def _strip(xs):
            return [x for i, x in enumerate(xs) if i not in none_idx]

        out_dense = tf.cond(
            tf.equal(counter, self.backward_passes_per_step),
            lambda: _strip(_boundary()), lambda: _strip(_skip()))
        out = []
        it = iter(out_dense)
        for i in range(len(grads)):
            out.append(None if i in none_idx else next(it))
        return out

    def apply_gradients(self, apply_closure: Callable, optimizer,
                        *args, **kwargs):
        """Run the optimizer's real apply on boundary calls; on skipped
        calls advance ``optimizer.iterations`` instead, so iteration-keyed
        LR schedules see every backward pass exactly like the reference's
        helper does (gradient_aggregation.py:229-268)."""

        def _apply():
            apply_closure(*args, **kwargs)
            return tf.identity(tf.convert_to_tensor(optimizer.iterations))

        def _skip():
            optimizer.iterations.assign_add(1)
            return tf.identity(tf.convert_to_tensor(optimizer.iterations))

        return tf.cond(tf.equal(self.counter, 0), _apply, _skip)
