"""HTTP key-value rendezvous store.

Reference analog: horovod/runner/http/http_server.py (scoped PUT/GET/DELETE
KV store, :35-134) + http_client.py. The launcher runs the server; workers
(and the elastic re-init path, reference gloo_context.cc:154-200) read keys
like ``rank_and_size/<hostname>/<local_rank>``.
"""

from __future__ import annotations

import json
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib import error as urlerror
from urllib import request as urlrequest


def _retrying(attempt_fn, attempts: int, backoff: float):
    """Run ``attempt_fn`` with bounded retries and jittered exponential
    backoff. Connection-level failures (URLError, reset, refused) are
    transient and retried; HTTP status errors (404 and friends) mean the
    server answered and raise immediately. Raises the last connection
    error once attempts are exhausted."""
    last: Exception = RuntimeError("no attempts made")
    for i in range(max(1, attempts)):
        try:
            return attempt_fn()
        except urlerror.HTTPError:
            raise  # the server answered; retrying won't change its mind
        except (urlerror.URLError, ConnectionError, OSError) as e:
            last = e
        if i + 1 < attempts:
            time.sleep(backoff * (2 ** i) * (0.5 + random.random() / 2))
    raise last


def http_get_with_retry(url: str, timeout: float = 2.0, attempts: int = 3,
                        backoff: float = 0.1) -> bytes:
    """GET with bounded retries — one transient ECONNREFUSED during worker
    startup must not abort a metrics scrape or fail a rendezvous."""

    def attempt() -> bytes:
        with urlrequest.urlopen(url, timeout=timeout) as resp:
            return resp.read()

    return _retrying(attempt, attempts, backoff)


class KVServer:
    """Threaded HTTP KV server (launcher side)."""

    def __init__(self, port: int = 0):
        self._store: Dict[str, bytes] = {}
        self._lock = threading.Lock()
        store = self._store
        lock = self._lock

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # silence
                pass

            def do_PUT(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                with lock:
                    store[self.path.lstrip("/")] = body
                self.send_response(200)
                self.end_headers()

            def do_GET(self):
                with lock:
                    val = store.get(self.path.lstrip("/"))
                if val is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(val)))
                self.end_headers()
                self.wfile.write(val)

            def do_DELETE(self):
                with lock:
                    existed = store.pop(self.path.lstrip("/"), None)
                self.send_response(200 if existed is not None else 404)
                self.end_headers()

        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    # direct (in-process) access for the launcher
    def put_json(self, key: str, value: Any):
        with self._lock:
            self._store[key] = json.dumps(value).encode()

    def get_json(self, key: str) -> Optional[Any]:
        with self._lock:
            val = self._store.get(key)
        return json.loads(val) if val is not None else None

    def delete(self, key: str):
        with self._lock:
            self._store.pop(key, None)

    def delete_prefix(self, prefix: str):
        """Drop every key under a prefix (generation GC: old topologies,
        worker states, go/reset records would otherwise accumulate for the
        life of an elastic job)."""
        with self._lock:
            for k in [k for k in self._store if k.startswith(prefix)]:
                del self._store[k]


class KVClient:
    """Worker-side client (reference: runner/http/http_client.py)."""

    def __init__(self, addr: str, port: int):
        self._base = f"http://{addr}:{port}/"

    def put_json(self, key: str, value: Any, timeout: float = 10.0,
                 attempts: int = 3, backoff: float = 0.1):
        # Bounded retry on connection-level failures: a worker PUTting its
        # READY record while the KV restarts (or before its listener is up)
        # must not fail the whole rendezvous on one ECONNREFUSED.
        body = json.dumps(value).encode()

        def attempt():
            req = urlrequest.Request(self._base + key, data=body,
                                     method="PUT")
            urlrequest.urlopen(req, timeout=timeout)

        _retrying(attempt, attempts, backoff)

    def get_json(self, key: str, timeout: float = 10.0,
                 poll_interval: float = 0.2) -> Optional[Any]:
        """GET, polling until the key exists or timeout elapses (rendezvous
        keys appear asynchronously)."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                with urlrequest.urlopen(self._base + key,
                                        timeout=timeout) as resp:
                    return json.loads(resp.read())
            except urlerror.HTTPError as e:
                if e.code != 404:
                    raise
            except urlerror.URLError:
                pass
            if time.monotonic() >= deadline:
                return None
            time.sleep(poll_interval)

    def delete(self, key: str, timeout: float = 10.0):
        req = urlrequest.Request(self._base + key, method="DELETE")
        try:
            urlrequest.urlopen(req, timeout=timeout)
        except urlerror.HTTPError:
            pass
