"""HTTP key-value rendezvous store.

Reference analog: horovod/runner/http/http_server.py (scoped PUT/GET/DELETE
KV store, :35-134) + http_client.py. The launcher runs the server; workers
(and the elastic re-init path, reference gloo_context.cc:154-200) read keys
like ``rank_and_size/<hostname>/<local_rank>``.

Control-plane availability (ISSUE 10): the KV is the single point every
elastic protocol rides (rendezvous, drain announcements, shard handoffs,
``serve_targets``), so it can optionally be **durable** and **fenced**:

- **Durability** — with a ``kv_dir`` (``HOROVOD_KV_DIR``) every mutation is
  appended to a write-ahead log (``wal.log``: ``[u32 len][u32 crc32]
  [payload]`` records) before it is visible, and the log is periodically
  compacted into an atomically-renamed snapshot (``snapshot.json``). A
  respawned server replays snapshot + WAL; replay is tolerant of a
  truncated tail and stops at the first corrupt record (the last complete
  record wins — a crash mid-append must not refuse startup). Replay time
  and WAL size are exported as ``hvd_kv_replay_seconds`` /
  ``hvd_kv_wal_bytes``.
- **Epoch fencing** — each durable server start bumps a persistent
  **control epoch**. Writers that claim an epoch (the elastic driver; the
  ``X-Hvd-Epoch`` header on the HTTP path) are rejected with a structured
  409 when their epoch is strictly older than the server's: a lingering
  pre-crash driver cannot mutate the store a recovered driver now owns.
  Epoch-less writes (worker READY records, heartbeats, drain announces)
  are never fenced — workers do not claim driver authority.

Replicated control plane (ISSUE 19) adds three mechanisms here (the
replica roles themselves live in ``runner/replica_kv.py``):

- **Prefix-sharded WALs** — a durable store keeps one WAL + snapshot per
  ``kv_keys`` shard (``core`` keeps the legacy ``wal.log`` /
  ``snapshot.json`` filenames, so pre-sharding directories replay
  unchanged); 1024-rank heartbeat appends no longer serialize behind
  resize records, and conformance audits each shard independently. Every
  logged op carries a server-global monotonic sequence ``"s"`` so the
  cross-shard commit order stays reconstructible.
- **Per-op sequence tokens** — mutations may carry ``X-Hvd-Client`` /
  ``X-Hvd-Seq`` headers; the server drops an exact ``(client, seq)``
  replay it has already applied. This is what makes a client retry after
  a timed-out-but-committed write safe (the PR-19 double-apply bugfix),
  and the tokens ride the WAL (``"c"``/``"n"``) so dedupe survives
  restarts and leader failover.
- **Client failover** — :class:`KVClient` optionally takes a replica
  endpoint list: it follows leader redirects (307 + ``X-Hvd-Leader``
  hint), rotates to the next replica on NotLeader/connection-refused,
  and keeps the same sequence token across retries of one logical op so
  failover never double-applies.
"""

from __future__ import annotations

import base64
import hashlib
import itertools
import json
import os
import random
import threading
import time
import uuid
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib import error as urlerror
from urllib import parse as urlparse
from urllib import request as urlrequest

from horovod_tpu.common import kv_keys

# HTTP header a writer uses to claim a control epoch; strictly-older
# claims are fenced with 409 + a JSON body naming both epochs.
EPOCH_HEADER = "X-Hvd-Epoch"
# per-op idempotency token: a stable client id + a per-client monotonic
# sequence number. A retried mutation reuses its token; the server drops
# exact (client, seq) replays it already applied.
CLIENT_HEADER = "X-Hvd-Client"
SEQ_HEADER = "X-Hvd-Seq"
# leader hint on a 307 redirect from a follower replica ("host:port")
LEADER_HEADER = "X-Hvd-Leader"

_WAL_FILE = "wal.log"
_SNAPSHOT_FILE = "snapshot.json"
_EPOCH_FILE = "epoch"
_VOTE_FILE = "vote"
# sanity ceiling on a single WAL record (a corrupt length header must not
# make replay try to allocate gigabytes)
_MAX_RECORD_BYTES = 64 << 20
# dedupe window: exact (client, seq) pairs remembered, FIFO-evicted. A
# retry lands within seconds of its original; 8192 mutations of headroom
# is orders of magnitude more than that window holds.
_MAX_TOKENS = 8192


def shard_wal_file(shard: str) -> str:
    """WAL filename for one shard — ``core`` keeps the legacy name so
    pre-sharding kv_dirs replay (and old tooling keeps working)."""
    return _WAL_FILE if shard == "core" else f"wal-{shard}.log"


def shard_snapshot_file(shard: str) -> str:
    return _SNAPSHOT_FILE if shard == "core" else f"snapshot-{shard}.json"


class StaleEpochError(RuntimeError):
    """A KV mutation claimed a control epoch older than the server's —
    the writer is a fenced-out stale driver and must stand down."""

    def __init__(self, current: int, offered: int):
        self.current = int(current)
        self.offered = int(offered)
        super().__init__(
            f"stale control epoch: offered {self.offered} < "
            f"current {self.current}")


def _retrying(attempt_fn, attempts: int, backoff: float,
              deadline: Optional[float] = None):
    """Run ``attempt_fn`` with bounded retries and jittered exponential
    backoff. Connection-level failures (URLError, reset, refused) are
    transient and retried; HTTP status errors (404 and friends) mean the
    server answered and raise immediately. ``deadline`` is a *monotonic*
    instant bounding total wall clock on top of the attempt bound — a
    hung (accept-but-never-respond) server otherwise costs
    attempts x timeout. Raises the last connection error once attempts
    or the deadline are exhausted."""
    last: Exception = RuntimeError("no attempts made")
    for i in range(max(1, attempts)):
        try:
            return attempt_fn()
        except urlerror.HTTPError:
            raise  # the server answered; retrying won't change its mind
        except (urlerror.URLError, ConnectionError, OSError) as e:
            last = e
        if deadline is not None and time.monotonic() >= deadline:
            break
        if i + 1 < attempts:
            delay = backoff * (2 ** i) * (0.5 + random.random() / 2)
            if deadline is not None:
                delay = min(delay, max(0.0, deadline - time.monotonic()))
            time.sleep(delay)
    raise last


def http_get_with_retry(url: str, timeout: float = 2.0, attempts: int = 3,
                        backoff: float = 0.1) -> bytes:
    """GET with bounded retries — one transient ECONNREFUSED during worker
    startup must not abort a metrics scrape or fail a rendezvous."""

    def attempt() -> bytes:
        with urlrequest.urlopen(url, timeout=timeout) as resp:
            return resp.read()

    return _retrying(attempt, attempts, backoff)


class _Wal:
    """Append-only mutation log + compacted snapshots for one KVServer.

    Record framing: ``[u32 len LE][u32 crc32 LE][payload]``; payload is a
    JSON op (``put``/``del``/``delp``, values base64). Appends are flushed
    per record so a SIGKILLed driver loses at most the record being
    written; replay tolerates exactly that (truncated tail, bad CRC) by
    stopping at the last complete record and truncating the garbage."""

    def __init__(self, kv_dir: str, snapshot_bytes: int,
                 wal_file: str = _WAL_FILE,
                 snap_file: str = _SNAPSHOT_FILE):
        self.dir = kv_dir
        self.snapshot_bytes = snapshot_bytes
        os.makedirs(kv_dir, exist_ok=True)
        self.wal_path = os.path.join(kv_dir, wal_file)
        self.snap_path = os.path.join(kv_dir, snap_file)
        self._f = None
        self.wal_bytes = 0
        self.replay_seconds = 0.0
        self.max_seq = 0              # highest "s" stamp seen (replay+snap)
        self.last_term = 0            # "t" stamp of the record AT max_seq
        self.tokens: List[Tuple[str, int]] = []  # (client, seq) in order

    # -- replay ---------------------------------------------------------------

    def replay(self, into: Optional[Dict[str, bytes]] = None) \
            -> Dict[str, bytes]:
        t0 = time.perf_counter()
        store: Dict[str, bytes] = {} if into is None else into
        snap = self._load_snapshot()
        if snap:
            store.update(snap)
        good_end = 0
        try:
            with open(self.wal_path, "rb") as f:
                data = f.read()
        except OSError:
            data = b""
        off = 0
        while off + 8 <= len(data):
            length = int.from_bytes(data[off:off + 4], "little")
            crc = int.from_bytes(data[off + 4:off + 8], "little")
            if length <= 0 or length > _MAX_RECORD_BYTES or \
                    off + 8 + length > len(data):
                break  # truncated tail / corrupt length: last record wins
            payload = data[off + 8:off + 8 + length]
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                break  # bit flip: stop at the last intact record
            try:
                op = json.loads(payload)
            except ValueError:
                break
            self._apply(store, op)
            if isinstance(op.get("s"), int) and op["s"] >= self.max_seq:
                self.max_seq = op["s"]
                if isinstance(op.get("t"), int):
                    self.last_term = op["t"]
            if op.get("c") is not None and isinstance(op.get("n"), int):
                self.tokens.append((str(op["c"]), op["n"]))
            off += 8 + length
            good_end = off
        if good_end < len(data):
            # drop the corrupt/truncated tail so fresh appends don't land
            # after garbage a future replay would stop at
            try:
                with open(self.wal_path, "r+b") as f:
                    f.truncate(good_end)
            except OSError:
                pass
        self._f = open(self.wal_path, "ab")
        self.wal_bytes = good_end
        self.replay_seconds = time.perf_counter() - t0
        return store

    def _load_snapshot(self) -> Dict[str, bytes]:
        """The compacted base state, or {} when absent/empty/corrupt — a
        bad snapshot degrades to a full-WAL replay, never a refusal to
        start."""
        try:
            with open(self.snap_path, "rb") as f:
                raw = f.read()
        except OSError:
            return {}
        if not raw:
            return {}
        try:
            doc = json.loads(raw)
            if isinstance(doc.get("seq"), int) and \
                    doc["seq"] >= self.max_seq:
                # compaction truncates the WAL, so the snapshot carries
                # the high-water "s" stamp — the global sequence must
                # stay monotone across restarts for cross-shard merges —
                # and the replication term at that stamp (the Raft
                # log-matching state compaction would otherwise lose)
                self.max_seq = doc["seq"]
                if isinstance(doc.get("term"), int):
                    self.last_term = doc["term"]
            return {k: base64.b64decode(v)
                    for k, v in doc.get("store", {}).items()}
        except (ValueError, TypeError, KeyError):
            return {}

    @staticmethod
    def _apply(store: Dict[str, bytes], op: dict):
        kind = op.get("op")
        if kind == "put":
            store[op["k"]] = base64.b64decode(op["v"])
        elif kind == "del":
            store.pop(op["k"], None)
        elif kind == "delp":
            for k in [k for k in store if k.startswith(op["p"])]:
                del store[k]

    # -- append + compaction (caller holds the server lock) -------------------

    def append(self, op: dict, store: Dict[str, bytes]):
        self.append_raw(op)
        if self.wal_bytes > self.snapshot_bytes:
            self.compact(store)

    def append_raw(self, op: dict):
        """Append without the compaction check — the sharded WAL manager
        compacts itself with a per-shard store slice."""
        payload = json.dumps(op).encode()
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        self._f.write(len(payload).to_bytes(4, "little") +
                      crc.to_bytes(4, "little") + payload)
        self._f.flush()
        self.wal_bytes += 8 + len(payload)

    def compact(self, store: Dict[str, bytes],
                seq: Optional[int] = None,
                term: Optional[int] = None):
        """Write the full store as a snapshot (write-then-rename, so a
        crash mid-compaction leaves the previous snapshot + full WAL —
        replay of both is idempotent), then start a fresh WAL."""
        tmp = self.snap_path + ".tmp"
        doc = {"store": {k: base64.b64encode(v).decode()
                         for k, v in store.items()},
               "ts": time.time()}
        if seq is not None:
            doc["seq"] = int(seq)
        if term is not None:
            doc["term"] = int(term)
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.snap_path)
        self._f.close()
        self._f = open(self.wal_path, "wb")
        self.wal_bytes = 0

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None

    # -- persistent control epoch --------------------------------------------

    def load_epoch(self) -> int:
        try:
            with open(os.path.join(self.dir, _EPOCH_FILE)) as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def store_epoch(self, epoch: int):
        path = os.path.join(self.dir, _EPOCH_FILE)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                f.write(str(int(epoch)))
            os.replace(tmp, path)
        except OSError:
            pass

    # -- persistent vote (replica election safety) ----------------------------

    def load_vote(self) -> Tuple[int, Optional[int]]:
        """The highest ``(epoch, voted_for)`` this replica ever granted,
        or ``(0, None)``. A voter that forgets its vote across a respawn
        could grant the same epoch to a second candidate — two leaders
        winning one term — so the grant is durable, like the epoch."""
        try:
            with open(os.path.join(self.dir, _VOTE_FILE)) as f:
                doc = json.loads(f.read())
            return int(doc["epoch"]), int(doc["cand"])
        except (OSError, ValueError, TypeError, KeyError):
            return 0, None

    def store_vote(self, epoch: int, cand: int) -> bool:
        """Durably record a grant. False = could not persist — the
        caller must NOT grant (an unrecorded vote is a forgettable one,
        exactly the double-vote hazard this file closes)."""
        path = os.path.join(self.dir, _VOTE_FILE)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"epoch": int(epoch), "cand": int(cand)}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return True
        except OSError:
            return False


class _ShardedWal:
    """One :class:`_Wal` per ``kv_keys`` shard, behind the same append/
    replay surface. Appends route by key (``kv_keys.shard_for_key``), so
    high-rate heartbeat records never share a log file — or a compaction
    stall — with core resize records. The in-memory store stays unified;
    only the durability layer shards. Replay order (core first, then the
    others into the same dict) keeps pre-sharding directories correct:
    a legacy ``wal.log`` may hold any key, and the shard logs replay
    over it."""

    def __init__(self, kv_dir: str, snapshot_bytes: int):
        os.makedirs(kv_dir, exist_ok=True)
        self.dir = kv_dir
        self._wals: Dict[str, _Wal] = {
            shard: _Wal(kv_dir, snapshot_bytes,
                        wal_file=shard_wal_file(shard),
                        snap_file=shard_snapshot_file(shard))
            for shard in kv_keys.SHARDS}
        self.max_seq = 0
        self.last_term = 0  # term of the record at the GLOBAL max_seq
        self.tokens: List[Tuple[str, int]] = []

    @staticmethod
    def shard_of(op: dict) -> str:
        if "k" in op:
            return kv_keys.shard_for_key(op["k"])
        return kv_keys.shard_for_prefix(op.get("p", ""))

    def replay(self) -> Dict[str, bytes]:
        store: Dict[str, bytes] = {}
        stamped = []
        for shard in kv_keys.SHARDS:
            w = self._wals[shard]
            w.replay(into=store)
            if w.max_seq >= self.max_seq:
                self.max_seq = w.max_seq
                self.last_term = w.last_term
            stamped.extend(w.tokens)
        # dedupe-table rebuild order across shards doesn't matter: the
        # table is an exact-match set, not a high-water mark
        self.tokens = stamped
        return store

    def append(self, op: dict, store: Dict[str, bytes]):
        shard = self.shard_of(op)
        w = self._wals[shard]
        if isinstance(op.get("s"), int):
            self.max_seq = max(self.max_seq, op["s"])
            if isinstance(op.get("t"), int):
                self.last_term = op["t"]
        w.append_raw(op)
        if w.wal_bytes > w.snapshot_bytes:
            w.compact({k: v for k, v in store.items()
                       if kv_keys.shard_for_key(k) == shard},
                      seq=self.max_seq, term=self.last_term)

    def compact_all(self, store: Dict[str, bytes]):
        """Rewrite every shard's snapshot from ``store`` and truncate all
        WALs — the resync path uses this to discard a diverged suffix."""
        for shard, w in self._wals.items():
            w.compact({k: v for k, v in store.items()
                       if kv_keys.shard_for_key(k) == shard},
                      seq=self.max_seq, term=self.last_term)

    def shard_bytes(self) -> Dict[str, int]:
        return {shard: w.wal_bytes for shard, w in self._wals.items()}

    @property
    def wal_bytes(self) -> int:
        return sum(w.wal_bytes for w in self._wals.values())

    @property
    def replay_seconds(self) -> float:
        return sum(w.replay_seconds for w in self._wals.values())

    def close(self):
        for w in self._wals.values():
            w.close()

    # the control epoch and the vote stay single dir-level files — they
    # fence/bind the whole store, not one shard
    def load_epoch(self) -> int:
        return self._wals["core"].load_epoch()

    def store_epoch(self, epoch: int):
        self._wals["core"].store_epoch(epoch)

    def load_vote(self) -> Tuple[int, Optional[int]]:
        return self._wals["core"].load_vote()

    def store_vote(self, epoch: int, cand: int) -> bool:
        return self._wals["core"].store_vote(epoch, cand)


class KVServer:
    """Threaded HTTP KV server (launcher side), optionally durable.

    ``kv_dir`` (unset = the historical in-memory store) enables the WAL +
    snapshot persistence and the persistent control epoch: every server
    start over the same directory is a **new epoch** (stored + 1), and
    mutations claiming a strictly-older epoch are fenced (HTTP 409 /
    :class:`StaleEpochError`). ``recovered`` is True when replay restored
    at least one key — the signal the elastic driver uses to resume an
    interrupted job instead of cold-starting generation 0."""

    _bump_epoch_on_start = True

    def __init__(self, port: int = 0, kv_dir: Optional[str] = None,
                 snapshot_bytes: Optional[int] = None):
        self._store: Dict[str, bytes] = {}
        self._lock = threading.Lock()
        self._wal: Optional[_ShardedWal] = None
        self.epoch = 0
        self.recovered = False
        # exact-match idempotency window: (client, seq) pairs already
        # applied, FIFO-evicted (dict keeps insertion order)
        self._applied: Dict[Tuple[str, int], bool] = {}
        self._seq = 0  # server-global op sequence ("s" WAL stamp)
        if kv_dir:
            if snapshot_bytes is None:
                from horovod_tpu.common.env_registry import env_int
                snapshot_bytes = env_int("HOROVOD_KV_SNAPSHOT_BYTES")
            self._wal = _ShardedWal(kv_dir, snapshot_bytes)
            self._store = self._wal.replay()
            self.recovered = bool(self._store)
            # a restarting standalone KV is a new driver incarnation →
            # bump; a restarting *replica* must NOT outrun its leader's
            # term (ReplicaKVServer overrides the class attr)
            self.epoch = self._wal.load_epoch() + \
                (1 if self._bump_epoch_on_start else 0)
            if self._bump_epoch_on_start:
                self._wal.store_epoch(self.epoch)
            self._seq = self._wal.max_seq
            for tok in self._wal.tokens[-_MAX_TOKENS:]:
                self._applied[tok] = True
            self._export_metrics()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # silence
                pass

            def _claimed_epoch(self) -> Optional[int]:
                raw = self.headers.get(EPOCH_HEADER)
                try:
                    return int(raw) if raw not in (None, "") else None
                except ValueError:
                    return None

            def _token(self) -> Optional[Tuple[str, int]]:
                cid = self.headers.get(CLIENT_HEADER)
                raw = self.headers.get(SEQ_HEADER)
                try:
                    return (cid, int(raw)) if cid and raw else None
                except ValueError:
                    return None

            def _send_fenced(self, e: StaleEpochError):
                body = json.dumps({
                    "error": "stale_epoch",
                    "current": e.current,
                    "offered": e.offered}).encode()
                self.send_response(409)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, doc, status: int = 200):
                body = json.dumps(doc).encode()
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_PUT(self):
                if server._route(self, "PUT"):
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                try:
                    server._put(self.path.lstrip("/"), body,
                                epoch=self._claimed_epoch(),
                                token=self._token())
                except StaleEpochError as e:
                    self._send_fenced(e)
                    return
                self.send_response(200)
                self.end_headers()

            def do_GET(self):
                if server._route(self, "GET"):
                    return
                path, _, query = self.path.partition("?")
                if path == "/replica_status":
                    self._send_json(server._replica_status())
                    return
                if path == "/_kv/keys":
                    q = urlparse.parse_qs(query)
                    prefix = q.get("prefix", [""])[0]
                    self._send_json(server.keys(prefix))
                    return
                with server._lock:
                    val = server._store.get(self.path.lstrip("/"))
                if val is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(val)))
                self.end_headers()
                self.wfile.write(val)

            def do_POST(self):
                if server._route(self, "POST"):
                    return
                self.send_response(404)
                self.end_headers()

            def do_DELETE(self):
                if server._route(self, "DELETE"):
                    return
                path, _, query = self.path.partition("?")
                try:
                    if path == "/_kv/prefix":
                        q = urlparse.parse_qs(query)
                        server.delete_prefix(q.get("p", [""])[0],
                                             epoch=self._claimed_epoch(),
                                             token=self._token())
                        existed = True
                    else:
                        existed = server.delete(self.path.lstrip("/"),
                                                epoch=self._claimed_epoch(),
                                                token=self._token())
                except StaleEpochError as e:
                    self._send_fenced(e)
                    return
                self.send_response(200 if existed else 404)
                self.end_headers()

        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- routing/extension hooks (the replica server overrides these) --------

    def _route(self, handler, method: str) -> bool:
        """Give a subclass first look at an HTTP request. Return True when
        the request was fully handled (response sent). The base server
        handles everything itself."""
        return False

    def _replica_status(self) -> dict:
        """The ``/replica_status`` document. An unreplicated KV reports
        itself as a single always-leader replica so hvd-top's KV-health
        banner works against either deployment shape."""
        with self._lock:
            return {"id": 0, "role": "leader", "leader": 0,
                    "epoch": self.epoch, "seq": self._seq,
                    "lease_age": 0.0, "replicas": 1,
                    "peers": {},
                    "shards": (self._wal.shard_bytes()
                               if self._wal is not None else {}),
                    "store_hash": self._store_hash_locked()}

    def _store_hash_locked(self) -> str:
        """Order-independent digest of the full store — the chaos soak's
        byte-identical-across-replicas oracle."""
        h = hashlib.sha256()
        for k in sorted(self._store):
            h.update(k.encode())
            h.update(b"\x00")
            h.update(self._store[k])
            h.update(b"\x01")
        return h.hexdigest()

    # -- durability internals -------------------------------------------------

    def _log_op(self, op: dict, epoch: Optional[int] = None,
                token: Optional[Tuple[str, int]] = None):
        """Caller holds self._lock. ``epoch`` (the writer's admitted
        control-epoch claim, when one was made) is recorded on the WAL
        op as ``"e"`` — replay ignores it, but the conformance checker
        (``horovod_tpu/verify/conformance.py``) replays the log against
        the epoch-monotonicity rule: a regression in the recorded claims
        is split-brain evidence. ``"s"`` is the server-global sequence
        (cross-shard merge order); ``"c"``/``"n"`` persist the client's
        idempotency token so the dedupe window survives restart and
        leader failover."""
        self._seq += 1
        if self._wal is not None:
            op = dict(op, s=self._seq)
            if epoch is not None:
                op["e"] = int(epoch)
            if token is not None:
                op["c"], op["n"] = token[0], int(token[1])
            self._wal.append(op, self._store)
            self._export_metrics()

    def _dedup_locked(self, token: Optional[Tuple[str, int]]) -> bool:
        """True when this exact (client, seq) token was already applied —
        the mutation is a retry of a committed op and must be dropped
        (acked as success, applied zero more times)."""
        if token is None:
            return False
        if token in self._applied:
            return True
        while len(self._applied) >= _MAX_TOKENS:
            self._applied.pop(next(iter(self._applied)))
        self._applied[token] = True
        return False

    def _export_metrics(self):
        try:
            from horovod_tpu.metrics.registry import get_registry
            reg = get_registry()
            reg.gauge("hvd_kv_wal_bytes",
                      "current control-plane WAL size").set(
                          self._wal.wal_bytes)
            reg.gauge("hvd_kv_replay_seconds",
                      "WAL+snapshot replay time at last KV start").set(
                          self._wal.replay_seconds)
        except Exception:  # noqa: BLE001 — metrics must not break the KV
            pass

    def _check_epoch_locked(self, claimed: Optional[int]):
        """Fence a claimed control epoch — caller holds ``self._lock`` so
        the check is atomic with the mutation it guards (a stale writer
        passing a separate pre-check could otherwise land its mutation
        AFTER a newer epoch advanced). Strictly-older raises
        StaleEpochError; newer advances and persists the server's epoch;
        epoch-less writes pass untouched."""
        if claimed is None:
            return
        if claimed < self.epoch:
            raise StaleEpochError(self.epoch, claimed)
        if claimed > self.epoch:
            self.epoch = claimed
            if self._wal is not None:
                self._wal.store_epoch(claimed)

    @staticmethod
    def _log_stale(e: StaleEpochError):
        try:
            from horovod_tpu.common.hvd_logging import get_logger
            get_logger("runner.kv").warning(
                "fenced stale control epoch: %s",
                json.dumps({"event": "stale_epoch_rejected",
                            "offered": e.offered, "current": e.current}))
            from horovod_tpu.common import journal
            journal.emit("kv", "stale_epoch_rejected",
                         control_epoch=e.current, offered=e.offered)
        except Exception:  # noqa: BLE001 — logging must not mask the 409
            pass

    def _put(self, key: str, body: bytes, epoch: Optional[int] = None,
             token: Optional[Tuple[str, int]] = None):
        try:
            with self._lock:
                self._check_epoch_locked(epoch)
                if self._dedup_locked(token):
                    return
                self._store[key] = body
                self._log_op({"op": "put", "k": key,
                              "v": base64.b64encode(body).decode()},
                             epoch=epoch, token=token)
        except StaleEpochError as e:
            self._log_stale(e)
            raise

    @property
    def wal_bytes(self) -> int:
        return self._wal.wal_bytes if self._wal is not None else 0

    @property
    def replay_seconds(self) -> float:
        return self._wal.replay_seconds if self._wal is not None else 0.0

    # -- lifecycle ------------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        if self._wal is not None:
            self._wal.close()

    # direct (in-process) access for the launcher
    def put_json(self, key: str, value: Any, epoch: Optional[int] = None):
        self._put(key, json.dumps(value).encode(), epoch=epoch)

    def get_json(self, key: str) -> Optional[Any]:
        with self._lock:
            val = self._store.get(key)
        return json.loads(val) if val is not None else None

    def delete(self, key: str, epoch: Optional[int] = None,
               token: Optional[Tuple[str, int]] = None) -> bool:
        try:
            with self._lock:
                self._check_epoch_locked(epoch)
                if self._dedup_locked(token):
                    return True  # the original delete committed
                existed = self._store.pop(key, None) is not None
                if existed:
                    self._log_op({"op": "del", "k": key}, epoch=epoch,
                                 token=token)
                return existed
        except StaleEpochError as e:
            self._log_stale(e)
            raise

    def delete_prefix(self, prefix: str, epoch: Optional[int] = None,
                      token: Optional[Tuple[str, int]] = None):
        """Drop every key under a prefix (generation GC: old topologies,
        worker states, go/reset records would otherwise accumulate for the
        life of an elastic job)."""
        try:
            with self._lock:
                self._check_epoch_locked(epoch)
                if self._dedup_locked(token):
                    return
                doomed = [k for k in self._store if k.startswith(prefix)]
                for k in doomed:
                    del self._store[k]
                if doomed:
                    self._log_op({"op": "delp", "p": prefix}, epoch=epoch,
                                 token=token)
        except StaleEpochError as e:
            self._log_stale(e)
            raise

    def keys(self, prefix: str = "") -> List[str]:
        """Snapshot of keys under a prefix (driver recovery rebuilds the
        expected-slot set from the persisted topology records)."""
        with self._lock:
            return [k for k in self._store if k.startswith(prefix)]


class NotLeaderError(ConnectionError):
    """Internal retry signal: the contacted replica cannot take the write
    (follower redirect or no leader elected yet). Subclasses
    ConnectionError so the shared retry loop treats it as transient —
    the client has already rotated to its next candidate endpoint."""


def replica_endpoints_from_env() -> Optional[List[str]]:
    """The ``HOROVOD_KV_REPLICA_ENDPOINTS`` list, or None when the
    control plane is unreplicated. Every worker-side KV client should
    pass this as ``endpoints=`` — a client pinned to one replica keeps
    working only until the first leader change."""
    from horovod_tpu.common.env_registry import env_str
    raw = env_str("HOROVOD_KV_REPLICA_ENDPOINTS")
    eps = [e.strip() for e in (raw or "").split(",") if e.strip()]
    return eps or None


class KVClient:
    """Worker-side client (reference: runner/http/http_client.py).

    ``epoch`` (optional) is attached to every mutation as the control-
    epoch claim; a fenced 409 raises :class:`StaleEpochError` so a stale
    driver fails loudly instead of silently mutating a store a recovered
    driver owns.

    ``endpoints`` (optional, ISSUE 19) is the replica endpoint list
    (``host:port`` strings). Mutations follow leader redirects (307 +
    ``X-Hvd-Leader``) and rotate to the next replica on NotLeader or
    connection-refused, all inside the caller's existing attempt/deadline
    budget. Every mutation carries a per-op sequence token generated
    ONCE per logical op — a retry (failover or timed-out-but-committed
    write) reuses it, so the server applies the op at most once."""

    def __init__(self, addr: str, port: int, epoch: Optional[int] = None,
                 endpoints: Optional[List[str]] = None):
        eps = [str(e).strip() for e in (endpoints or []) if str(e).strip()]
        primary = f"{addr}:{port}"
        if primary not in eps:
            eps.insert(0, primary)
        self._endpoints = eps
        self._active = 0
        self.epoch = epoch
        self._cid = uuid.uuid4().hex[:12]
        self._op_seq = itertools.count(1)

    @property
    def _base(self) -> str:
        return f"http://{self._endpoints[self._active]}/"

    def _rotate(self):
        self._active = (self._active + 1) % len(self._endpoints)

    def _next_token(self) -> Tuple[str, int]:
        return (self._cid, next(self._op_seq))

    def _headers(self, token: Optional[Tuple[str, int]] = None) -> dict:
        h: Dict[str, str] = {}
        if self.epoch is not None:
            h[EPOCH_HEADER] = str(self.epoch)
        if token is not None:
            h[CLIENT_HEADER] = token[0]
            h[SEQ_HEADER] = str(token[1])
        return h

    def _mutation_http_error(self, e: urlerror.HTTPError):
        """Classify a mutation's HTTP error: follow a leader redirect,
        rotate on no-leader, surface a fence, re-raise the rest."""
        if e.code == 307:
            hint = e.headers.get(LEADER_HEADER)
            if hint and hint in self._endpoints:
                self._active = self._endpoints.index(hint)
            elif hint:
                self._endpoints.append(hint)
                self._active = len(self._endpoints) - 1
            else:
                self._rotate()
            raise NotLeaderError(f"redirected to leader {hint}") from e
        if e.code == 503:
            self._rotate()
            raise NotLeaderError("replica has no leader") from e
        self._raise_if_fenced(e)

    @staticmethod
    def _raise_if_fenced(e: urlerror.HTTPError):
        if e.code != 409:
            raise e
        try:
            body = json.loads(e.read())
            raise StaleEpochError(body["current"], body["offered"]) from e
        except (ValueError, KeyError):
            raise e from None

    def put_json(self, key: str, value: Any, timeout: float = 10.0,
                 attempts: int = 3, backoff: float = 0.1,
                 deadline: Optional[float] = None):
        """Bounded retry on connection-level failures: a worker PUTting
        its READY record while the KV restarts (or before its listener is
        up) must not fail the whole rendezvous on one ECONNREFUSED.
        ``deadline`` (seconds of total wall clock) additionally bounds the
        whole call — per-attempt retries alone let a hung
        (accept-but-never-respond) driver wedge a heartbeat/handoff
        thread for attempts x timeout."""
        body = json.dumps(value).encode()
        token = self._next_token()  # ONE token per logical op: retries
        # (failover, timed-out-but-committed) reuse it, so the server
        # applies the mutation at most once
        abs_deadline = time.monotonic() + deadline \
            if deadline is not None else None

        def attempt():
            per = timeout
            if abs_deadline is not None:
                per = max(0.05, min(per, abs_deadline - time.monotonic()))
            req = urlrequest.Request(self._base + key, data=body,
                                     method="PUT",
                                     headers=self._headers(token))
            try:
                urlrequest.urlopen(req, timeout=per)
            except urlerror.HTTPError as e:
                self._mutation_http_error(e)
            except (urlerror.URLError, ConnectionError, OSError):
                self._rotate()
                raise

        _retrying(attempt, attempts, backoff, deadline=abs_deadline)

    def get_json(self, key: str, timeout: float = 10.0,
                 poll_interval: float = 0.2) -> Optional[Any]:
        """GET, polling until the key exists or timeout elapses (rendezvous
        keys appear asynchronously). ``timeout`` is the total budget: each
        attempt's transport timeout is capped at what remains, so a hung
        server cannot stretch one poll past the window."""
        deadline = time.monotonic() + timeout
        while True:
            per = max(0.05, min(timeout, deadline - time.monotonic()))
            try:
                with urlrequest.urlopen(self._base + key,
                                        timeout=per) as resp:
                    return json.loads(resp.read())
            except urlerror.HTTPError as e:
                if e.code in (503, 307):
                    self._rotate()  # replica mid-election: try a peer
                elif e.code != 404:
                    raise
            except (urlerror.URLError, ConnectionError, OSError):
                # unreachable, reset, or hung past the per-attempt
                # timeout (a raw socket TimeoutError when the server
                # accepts but never responds) — poll until the window
                # closes (rotating across replicas when we have them)
                self._rotate()
            if time.monotonic() >= deadline:
                return None
            time.sleep(poll_interval)

    def get_json_leader(self, key: str, timeout: float = 3.0,
                        attempts: int = 6, backoff: float = 0.2,
                        deadline: Optional[float] = None) -> Optional[Any]:
        """Read ``key`` through the current LEADER (``/_replica/read``),
        never a follower's local store. For reads whose staleness is a
        correctness hazard — e.g. the driver's ownership check after a
        fence, where a lagging follower's old owner stamp would let a
        genuinely deposed driver adopt the rival's epoch and write on.
        Follows the 307 leader redirect (urllib follows it for GETs) and
        rotates on no-leader; raises the last connection error when no
        leader is reachable within the attempt/deadline budget."""
        url = "_replica/read?" + urlparse.urlencode({"k": key})
        abs_deadline = time.monotonic() + deadline \
            if deadline is not None else None

        def attempt():
            try:
                with urlrequest.urlopen(self._base + url,
                                        timeout=timeout) as resp:
                    doc = json.loads(resp.read())
            except urlerror.HTTPError as e:
                if e.code in (503, 307):
                    self._rotate()
                    raise NotLeaderError("replica has no leader") from e
                raise
            except (urlerror.URLError, ConnectionError, OSError):
                self._rotate()
                raise
            if not doc.get("found"):
                return None
            return json.loads(base64.b64decode(doc["v"]))

        return _retrying(attempt, attempts, backoff, deadline=abs_deadline)

    def delete(self, key: str, timeout: float = 10.0, attempts: int = 3,
               backoff: float = 0.1):
        token = self._next_token()

        def attempt():
            req = urlrequest.Request(self._base + key, method="DELETE",
                                     headers=self._headers(token))
            try:
                urlrequest.urlopen(req, timeout=timeout)
            except urlerror.HTTPError as e:
                if e.code in (404, 200):
                    return
                self._mutation_http_error(e)
            except (urlerror.URLError, ConnectionError, OSError):
                self._rotate()
                raise

        _retrying(attempt, attempts, backoff)

    def delete_prefix(self, prefix: str, timeout: float = 10.0,
                      attempts: int = 3, backoff: float = 0.1):
        token = self._next_token()
        url = "_kv/prefix?" + urlparse.urlencode({"p": prefix})

        def attempt():
            req = urlrequest.Request(self._base + url, method="DELETE",
                                     headers=self._headers(token))
            try:
                urlrequest.urlopen(req, timeout=timeout)
            except urlerror.HTTPError as e:
                if e.code == 404:
                    return
                self._mutation_http_error(e)
            except (urlerror.URLError, ConnectionError, OSError):
                self._rotate()
                raise

        _retrying(attempt, attempts, backoff)

    def keys(self, prefix: str = "", timeout: float = 5.0,
             attempts: int = 3, backoff: float = 0.1) -> List[str]:
        url = "_kv/keys?" + urlparse.urlencode({"prefix": prefix})

        def attempt():
            try:
                with urlrequest.urlopen(self._base + url,
                                        timeout=timeout) as resp:
                    return json.loads(resp.read())
            except urlerror.HTTPError as e:
                if e.code in (503, 307):
                    self._rotate()
                    raise NotLeaderError("replica mid-election") from e
                raise
            except (urlerror.URLError, ConnectionError, OSError):
                self._rotate()
                raise

        return _retrying(attempt, attempts, backoff)

    def replica_status(self, timeout: float = 2.0) -> Optional[dict]:
        """Best-effort ``/replica_status`` probe of the active endpoint
        (None when unreachable)."""
        try:
            with urlrequest.urlopen(self._base + "replica_status",
                                    timeout=timeout) as resp:
                return json.loads(resp.read())
        except (urlerror.URLError, ConnectionError, OSError, ValueError):
            return None
