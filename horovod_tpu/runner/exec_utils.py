"""Worker process spawning — local subprocess or ssh.

Reference analog: horovod/runner/common/util/safe_shell_exec.py (exec with
output forwarding + termination) and the per-slot ssh command construction
in runner/gloo_run.py:114-185.
"""

from __future__ import annotations

import os
import shlex
import signal
import subprocess
import sys
import threading
from typing import Dict, List, Optional

LOCAL_HOSTNAMES = {"localhost", "127.0.0.1", os.uname().nodename}


def is_local(hostname: str) -> bool:
    return hostname in LOCAL_HOSTNAMES


def build_command(hostname: str, command: List[str],
                  env: Dict[str, str], ssh_port: Optional[int] = None,
                  ) -> List[str]:
    """Local: run directly with env. Remote: ssh with inline exports
    (reference: gloo_run.py get_remote_command)."""
    if is_local(hostname):
        return command
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
    remote = f"cd {shlex.quote(os.getcwd())} && env {exports} " + \
        " ".join(shlex.quote(c) for c in command)
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    return ssh + [hostname, remote]


class WorkerProcess:
    """A spawned worker with output forwarding and a tag prefix
    (reference: safe_shell_exec forwarding threads)."""

    def __init__(self, hostname: str, rank: int, command: List[str],
                 env: Dict[str, str], prefix_output: bool = True,
                 capture: bool = False):
        self.hostname = hostname
        self.rank = rank
        full_env = dict(os.environ)
        full_env.update(env)
        # keep launcher-spawned workers off any single-tenant accelerator
        # relay; the training script opts back in explicitly if needed.
        cmd = build_command(hostname, command, env)
        self.captured: List[str] = []
        self._capture = capture
        self.proc = subprocess.Popen(
            cmd, env=full_env if is_local(hostname) else None,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True)
        self._fwd = threading.Thread(
            target=self._forward, args=(prefix_output,), daemon=True)
        self._fwd.start()

    def _forward(self, prefix: bool):
        tag = f"[{self.rank}]<stdout>:" if prefix else ""
        for line in self.proc.stdout:
            text = line.decode(errors="replace")
            if self._capture:
                self.captured.append(text)
            sys.stdout.write(f"{tag}{text}" if tag else text)
            sys.stdout.flush()

    def wait(self, timeout: Optional[float] = None) -> int:
        rc = self.proc.wait(timeout=timeout)
        self._fwd.join(timeout=5)
        return rc

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def terminate(self):
        if self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)
            except ProcessLookupError:
                pass

    def kill(self):
        if self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
            except ProcessLookupError:
                pass
