"""Worker process spawning — local subprocess or ssh.

Reference analog: horovod/runner/common/util/safe_shell_exec.py (exec with
output forwarding + termination) and the per-slot ssh command construction
in runner/gloo_run.py:114-185.
"""

from __future__ import annotations

import os
import shlex
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

LOCAL_HOSTNAMES = {"localhost", "127.0.0.1", os.uname().nodename}


def is_local(hostname: str) -> bool:
    return hostname in LOCAL_HOSTNAMES


def build_command(hostname: str, command: List[str],
                  env: Dict[str, str], ssh_port: Optional[int] = None,
                  ) -> List[str]:
    """Local: run directly with env. Remote: ssh with inline exports
    (reference: gloo_run.py get_remote_command)."""
    if is_local(hostname):
        return command
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
    remote = f"cd {shlex.quote(os.getcwd())} && env {exports} " + \
        " ".join(shlex.quote(c) for c in command)
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    return ssh + [hostname, remote]


def _tail_forward(path: str, tag: str, done_fn, from_offset: int = 0):
    """Poll-tail ``path`` and forward complete lines to stdout with
    ``tag`` — the durable-mode analog of the pipe-forwarding thread.
    Stops once ``done_fn()`` is true and the file is drained."""
    f = None
    try:
        while f is None:
            try:
                f = open(path, "rb")
            except OSError:
                if done_fn():
                    return
                time.sleep(0.2)
        f.seek(from_offset)
        while True:
            line = f.readline()
            if line:
                text = line.decode(errors="replace")
                sys.stdout.write(f"{tag}{text}" if tag else text)
                sys.stdout.flush()
            else:
                if done_fn():
                    # final drain: bytes may have landed between the EOF
                    # read and the done check (tagged per line like the
                    # main loop, or a 64-rank job's exit lines would be
                    # unattributable)
                    tail = f.read().decode(errors="replace")
                    for text in tail.splitlines(keepends=True):
                        sys.stdout.write(f"{tag}{text}" if tag else text)
                    if tail:
                        sys.stdout.flush()
                    return
                time.sleep(0.2)
    finally:
        if f is not None:
            f.close()


class AdoptedWorker:
    """A live worker a *recovered* driver re-learned from its KV
    heartbeats instead of spawning (the original driver that forked it is
    dead, so there is no child-process handle to poll).

    Liveness: a signal-0 pid probe on local hosts, heartbeat freshness
    (wall-clock ``ts`` the driver refreshes from the KV each scan)
    elsewhere. The exit *code* of a dead adopted worker is unknowable —
    poll() reports 1 and the driver's reap path consults the worker-state
    registry to reinterpret SUCCESS/DRAINED records as clean exits."""

    adopted = True

    def __init__(self, hostname: str, rank, pid: int,
                 heartbeat_timeout: float = 10.0,
                 log_path: Optional[str] = None):
        self.hostname = hostname
        self.rank = rank
        self.pid = int(pid or 0)
        self._timeout = heartbeat_timeout
        self._last_beat = time.time()
        self._local = is_local(hostname)
        self._code: Optional[int] = None
        if log_path:
            # resume forwarding the worker's log from where it stands now
            # (the outage window's lines stay in the file)
            try:
                offset = os.path.getsize(log_path)
            except OSError:
                offset = 0
            threading.Thread(
                target=_tail_forward,
                args=(log_path, f"[{rank}]<stdout>:",
                      lambda: self.poll() is not None, offset),
                daemon=True).start()

    def note_heartbeat(self, ts: float):
        self._last_beat = max(self._last_beat, float(ts))

    def poll(self) -> Optional[int]:
        if self._code is not None:
            return self._code
        if self._local and self.pid:
            try:
                os.kill(self.pid, 0)
            except ProcessLookupError:
                self._code = 1
                return self._code
            except PermissionError:
                pass  # pid exists but isn't ours — fall through to the
                # heartbeat check: it may be a recycled pid, not the
                # worker (a dead worker must not look alive forever)
        # Heartbeat age is authoritative even when the pid probe says
        # alive: pid reuse (or a wedged worker that stopped beating
        # against a reachable KV) would otherwise never be reaped and
        # the slot would hang the next go-barrier indefinitely.
        if time.time() - self._last_beat > self._timeout:
            self._code = 1
            return self._code
        return None

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = time.monotonic() + (timeout if timeout is not None
                                       else float("inf"))
        while self.poll() is None:
            if time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired("adopted-worker",
                                                timeout or 0)
            time.sleep(0.1)
        return self._code

    def _signal(self, sig):
        if not (self._local and self.pid):
            return  # remote adoptee: the host-side agent owns its death
        try:
            # workers are session leaders (start_new_session=True), so the
            # pid doubles as the process-group id
            os.killpg(self.pid, sig)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                os.kill(self.pid, sig)
            except (ProcessLookupError, PermissionError):
                pass

    def terminate(self):
        self._signal(signal.SIGTERM)

    def kill(self):
        self._signal(signal.SIGKILL)


class WorkerProcess:
    """A spawned worker with output forwarding and a tag prefix
    (reference: safe_shell_exec forwarding threads).

    ``log_path`` switches stdout/stderr from a pipe to an append-mode
    file, tail-forwarded instead of pipe-forwarded. This is what the
    crash-recoverable driver uses: a pipe dies with its reader, so a
    SIGKILLed driver would EPIPE every worker's next print — with a file,
    workers keep writing through the outage and the respawned driver
    resumes tailing (:class:`AdoptedWorker`)."""

    def __init__(self, hostname: str, rank: int, command: List[str],
                 env: Dict[str, str], prefix_output: bool = True,
                 capture: bool = False, log_path: Optional[str] = None):
        self.hostname = hostname
        self.rank = rank
        full_env = dict(os.environ)
        full_env.update(env)
        # keep launcher-spawned workers off any single-tenant accelerator
        # relay; the training script opts back in explicitly if needed.
        cmd = build_command(hostname, command, env)
        self.captured: List[str] = []
        self._capture = capture
        self.log_path = log_path
        if log_path:
            os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
            self._logf = open(log_path, "ab")
            offset = self._logf.tell()
            self.proc = subprocess.Popen(
                cmd, env=full_env if is_local(hostname) else None,
                stdout=self._logf, stderr=subprocess.STDOUT,
                start_new_session=True)
            tag = f"[{rank}]<stdout>:" if prefix_output else ""
            self._fwd = threading.Thread(
                target=_tail_forward,
                args=(log_path, tag,
                      lambda: self.proc.poll() is not None, offset),
                daemon=True)
        else:
            self.proc = subprocess.Popen(
                cmd, env=full_env if is_local(hostname) else None,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                start_new_session=True)
            self._fwd = threading.Thread(
                target=self._forward, args=(prefix_output,), daemon=True)
        self._fwd.start()

    def _forward(self, prefix: bool):
        tag = f"[{self.rank}]<stdout>:" if prefix else ""
        for line in self.proc.stdout:
            text = line.decode(errors="replace")
            if self._capture:
                self.captured.append(text)
            sys.stdout.write(f"{tag}{text}" if tag else text)
            sys.stdout.flush()

    def wait(self, timeout: Optional[float] = None) -> int:
        rc = self.proc.wait(timeout=timeout)
        self._fwd.join(timeout=5)
        return rc

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def terminate(self):
        if self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)
            except ProcessLookupError:
                pass

    def kill(self):
        if self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
            except ProcessLookupError:
                pass
