"""Host parsing and slot assignment.

Reference analog: horovod/runner/common/util/hosts.py — ``parse_hosts``
("host1:4,host2:2" specs) and ``get_host_assignments`` producing SlotInfo
records with the full rank topology (rank / local_rank / cross_rank and the
three sizes) that the launcher exports as the worker env contract
(reference: runner/gloo_run.py:65-78).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence


@dataclasses.dataclass
class HostInfo:
    hostname: str
    slots: int

    @staticmethod
    def from_string(spec: str) -> "HostInfo":
        if ":" in spec:
            host, slots = spec.rsplit(":", 1)
            return HostInfo(host, int(slots))
        return HostInfo(spec, 1)


@dataclasses.dataclass
class SlotInfo:
    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int

    def to_env(self) -> Dict[str, str]:
        return {
            "HOROVOD_HOSTNAME": self.hostname,
            "HOROVOD_RANK": str(self.rank),
            "HOROVOD_LOCAL_RANK": str(self.local_rank),
            "HOROVOD_CROSS_RANK": str(self.cross_rank),
            "HOROVOD_SIZE": str(self.size),
            "HOROVOD_LOCAL_SIZE": str(self.local_size),
            "HOROVOD_CROSS_SIZE": str(self.cross_size),
        }


def parse_hosts(hosts_string: str) -> List[HostInfo]:
    """Parse "host1:2,host2:4" (reference: hosts.py parse_hosts)."""
    return [HostInfo.from_string(s) for s in hosts_string.split(",") if s]


def get_host_assignments(hosts: Sequence[HostInfo], min_np: int,
                         max_np: int = None) -> List[SlotInfo]:
    """Assign ranks to host slots (reference: hosts.py
    get_host_assignments): ranks fill hosts in order; local_rank is the
    index within a host; cross_rank is the index of the host among hosts
    that also have that local_rank."""
    max_np = max_np if max_np is not None else min_np
    total = sum(h.slots for h in hosts)
    if total < min_np:
        raise ValueError(
            f"requested at least {min_np} processes but hosts provide only "
            f"{total} slots")
    np_ = min(total, max_np)

    # rank-ordered placement
    placements: List = []  # (host_idx, local_rank)
    for host_idx, h in enumerate(hosts):
        for local_rank in range(h.slots):
            if len(placements) == np_:
                break
            placements.append((host_idx, local_rank))

    local_sizes: Dict[int, int] = {}
    for host_idx, _ in placements:
        local_sizes[host_idx] = local_sizes.get(host_idx, 0) + 1
    # cross_size per local_rank = number of hosts having that local_rank
    cross_sizes: Dict[int, int] = {}
    for _, local_rank in placements:
        cross_sizes[local_rank] = cross_sizes.get(local_rank, 0) + 1

    out: List[SlotInfo] = []
    for rank, (host_idx, local_rank) in enumerate(placements):
        cross_rank = sum(1 for (h2, l2) in placements[:rank]
                         if l2 == local_rank)
        out.append(SlotInfo(
            hostname=hosts[host_idx].hostname,
            rank=rank,
            local_rank=local_rank,
            cross_rank=cross_rank,
            size=np_,
            local_size=local_sizes[host_idx],
            cross_size=cross_sizes[local_rank],
        ))
    return out
