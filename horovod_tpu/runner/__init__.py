"""Programmatic launcher (reference: horovod/runner/__init__.py:206 —
``horovod.run(func, np=N)`` returning each rank's result).

Reuses the hvdrun-tpu machinery (rendezvous KV, env contract, fail-fast
supervision) with a worker command that executes a cloudpickled function
and ships its return value back through a shared results directory.
"""

from __future__ import annotations

import os
import sys
import tempfile
from typing import Any, List, Optional


def run(func,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        np: int = 1,
        hosts: Optional[str] = None,
        start_timeout: float = 120.0,
        extra_args: Optional[List[str]] = None,
        verbose: bool = False) -> List[Any]:
    """Run ``func(*args, **kwargs)`` on ``np`` coordinated worker processes
    and return the per-rank results in rank order (reference:
    runner/__init__.py run()).

    ``hosts`` takes the launcher's "host:slots,..." syntax; workers ship
    results (and fetch the function) through the launcher's rendezvous KV
    over HTTP, so remote hosts need no shared filesystem (the role of the
    reference's task service; a shared results directory is used as a
    fast path when present).
    ``extra_args`` passes additional hvdrun-tpu flags (engine knobs).
    """
    import base64
    import cloudpickle  # lazy: CLI launches must not require it

    from horovod_tpu.runner import launch as launch_lib
    from horovod_tpu.runner.http_kv import KVServer

    kwargs = kwargs or {}

    def wrapped():
        return func(*args, **kwargs)

    with tempfile.TemporaryDirectory(prefix="hvdtpu_run_") as td:
        fn_blob = cloudpickle.dumps(wrapped)
        fn_path = os.path.join(td, "func.pkl")
        with open(fn_path, "wb") as f:
            f.write(fn_blob)
        command = [sys.executable, "-m", "horovod_tpu.runner.run_task",
                   fn_path, td]
        argv = ["-np", str(np),
                "-H", hosts or f"localhost:{np}",
                "--start-timeout", str(start_timeout)]
        if verbose:
            argv.append("--verbose")
        argv += list(extra_args or [])
        argv += ["--"] + command
        try:
            parsed = launch_lib.make_parser().parse_args(argv)
        except SystemExit as e:
            # library API: a bad extra_args flag must raise, not kill the
            # caller's process via argparse's sys.exit
            raise ValueError(
                f"invalid launcher arguments {extra_args!r}") from e
        parsed.command = command

        import time
        deadline = time.monotonic() + start_timeout
        all_started = [False]
        kv = KVServer().start()
        from horovod_tpu.common import kv_keys
        kv.put_json(kv_keys.task_fn(),
                    {"data": base64.b64encode(fn_blob).decode()},
                    epoch=kv.epoch)

        def not_started_by_deadline():
            if all_started[0] or time.monotonic() < deadline:
                return None
            missing = [r for r in range(np)
                       if not os.path.exists(
                           os.path.join(td, f"started.{r}"))
                       and kv.get_json(kv_keys.task_started(r)) is None]
            if missing:
                return (f"ranks {missing} did not start within "
                        f"{start_timeout}s")
            all_started[0] = True
            return None

        try:
            rc = launch_lib.run_static(
                parsed, liveness_check=not_started_by_deadline, kv=kv)
            if rc != 0:
                raise RuntimeError(
                    f"horovod_tpu.run failed with exit code {rc}")
            results = []
            for r in range(np):
                path = os.path.join(td, f"result.{r}.pkl")
                if os.path.exists(path):
                    with open(path, "rb") as f:
                        results.append(cloudpickle.load(f))
                    continue
                blob = kv.get_json(kv_keys.task_result(0, r))
                if blob is None:
                    raise RuntimeError(f"no result from rank {r}")
                results.append(cloudpickle.loads(
                    base64.b64decode(blob["data"])))
            return results
        finally:
            kv.stop()
