"""``hvdrun-tpu`` — the launcher CLI.

Reference analog: horovod/runner/launch.py (argparse surface mapping engine
knobs to env, :734-758 static-vs-elastic dispatch) + gloo_run.py
(rendezvous server, host assignment, per-slot env, worker spawn,
:226-271,187-211).

Static flow: allocate controller+data ports, start the rendezvous KV,
publish per-slot topology, spawn one worker per slot with the
``HOROVOD_*`` env contract, fail fast if any worker fails.
"""

from __future__ import annotations

import argparse
import os
import socket
import sys
import time
from typing import List, Optional

from horovod_tpu.runner import hosts as hosts_lib
from horovod_tpu.runner.exec_utils import WorkerProcess, is_local
from horovod_tpu.runner.http_kv import KVServer

# ssh reachability results are cached here and trusted for this long
# (reference: launch.py CACHE_FOLDER + CACHE_STALENESS_THRESHOLD_MINUTES)
SSH_CACHE_FILE = os.path.join(os.path.expanduser("~"), ".horovod_tpu",
                              "ssh_reachability.json")
SSH_CACHE_STALENESS_S = 60 * 60
SSH_ATTEMPTS = 3
SSH_CONNECT_TIMEOUT_S = 10


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("0.0.0.0", 0))
        return s.getsockname()[1]


def free_ports(n: int) -> List[int]:
    """Allocate ``n`` distinct free ports, holding all the sockets bound
    simultaneously — sequential free_port() calls can hand back the same
    port twice once the first socket is closed."""
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("0.0.0.0", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def check_build(verbose: bool = False) -> str:
    """Summarize what this installation can do — frameworks, controllers,
    and TPU features (reference: launch.py:110-146 check_build; the
    controller/ops sections are re-interpreted for the TPU stack)."""
    import importlib.util as iu

    def have(mod):
        try:
            return iu.find_spec(mod) is not None
        except (ImportError, ValueError):
            return False

    try:
        from horovod_tpu.engine import bindings
        bindings.load_library()
        engine_ok = True
    except Exception:  # noqa: BLE001 — any load failure means "not built"
        engine_ok = False
    try:
        from horovod_tpu import __version__ as version
    except ImportError:
        version = "dev"

    def mark(v):
        return "X" if v else " "

    lines = [
        f"horovod_tpu v{version}:",
        "",
        "Available Frameworks:",
        f"    [{mark(have('jax'))}] JAX",
        f"    [{mark(have('tensorflow'))}] TensorFlow",
        f"    [{mark(have('torch'))}] PyTorch",
        f"    [{mark(have('keras'))}] Keras",
        "",
        "Available Controllers:",
        f"    [{mark(engine_ok)}] native engine (TCP / loopback)",
        "",
        "Available Tensor Operations:",
        f"    [{mark(have('jax'))}] XLA collectives (ICI/DCN)",
        f"    [{mark(engine_ok)}] host data plane (ring + star)",
        f"    [{mark(have('jax'))}] Pallas flash attention",
        "",
        "Available Integrations:",
        f"    [{mark(have('pyspark'))}] Spark",
        f"    [{mark(have('ray'))}] Ray",
    ]
    out = "\n".join(lines)
    if verbose and not engine_ok:
        out += ("\n\nnative engine unavailable: build it with "
                "`make -C horovod_tpu/engine`")
    return out


# YAML --config-file sections -> argparse dest names (reference schema:
# runner/common/util/config_parser.py set_args_from_config)
_CONFIG_SCHEMA = {
    "params": {
        "fusion_threshold_mb": "fusion_threshold_mb",
        "cycle_time_ms": "cycle_time_ms",
        "cache_capacity": "cache_capacity",
        "hierarchical_allreduce": "hierarchical_allreduce",
    },
    "autotune": {
        "enabled": "autotune",
        "log_file": "autotune_log",
        "warmup_samples": "autotune_warmup_samples",
        "steps_per_sample": "autotune_steps",
        "sample_cycles": "autotune_sample_cycles",
    },
    "timeline": {
        "filename": "timeline_filename",
        "mark_cycles": "timeline_mark_cycles",
    },
    "stall_check": {
        "warning_time_seconds": "stall_check_time_seconds",
        "shutdown_time_seconds": "stall_shutdown_time_seconds",
    },
}


def apply_config_file(parser: argparse.ArgumentParser, path: str) -> None:
    """Fold a YAML config into the parser's defaults, so explicit CLI flags
    win over the file and the file wins over built-in defaults (reference:
    launch.py:293,513-517; the reference's position-relative override order
    is simplified to CLI-beats-config)."""
    import yaml  # declared dependency (pyproject.toml)

    with open(path) as f:
        config = yaml.safe_load(f) or {}
    defaults = {}
    for section, mapping in _CONFIG_SCHEMA.items():
        values = config.get(section) or {}
        for key, dest in mapping.items():
            if key in values and values[key] is not None:
                defaults[dest] = values[key]
    stall = config.get("stall_check") or {}
    if "enabled" in stall:
        defaults["no_stall_check"] = not stall["enabled"]
    parser.set_defaults(**defaults)


def _load_ssh_cache() -> dict:
    import json
    try:
        with open(SSH_CACHE_FILE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _effective_ssh_user(host: str) -> str:
    """The user ssh will authenticate as for ``host``: an explicit
    ``user@host`` prefix wins, else the invoking user. Folding this into the
    cache key keeps a success for one credential set from being trusted for
    another."""
    if "@" in host:
        return host.split("@", 1)[0]
    import getpass
    try:
        return getpass.getuser()
    except Exception:
        return os.environ.get("USER", "?")


def _ssh_cache_key(host: str, ssh_port) -> str:
    return f"{_effective_ssh_user(host)}@{host}:{ssh_port or 22}"


def _store_ssh_cache(cache: dict, now: Optional[float] = None) -> None:
    import json
    if now is not None:
        # Prune entries past the staleness window on every store — they can
        # never satisfy a lookup again, and without pruning the file grows
        # with every host/credential combination ever probed.
        cache = {k: t for k, t in cache.items()
                 if now - t < SSH_CACHE_STALENESS_S}
    try:
        os.makedirs(os.path.dirname(SSH_CACHE_FILE), exist_ok=True)
        with open(SSH_CACHE_FILE, "w") as f:
            json.dump(cache, f)
    except OSError:
        pass  # cache is an optimization; never fail the launch over it


def check_hosts_ssh(hostnames, ssh_port=None) -> List[str]:
    """Return the subset of remote hosts that are NOT ssh-reachable.
    Successes are cached for SSH_CACHE_STALENESS_S so repeated launches
    skip the probe (reference: launch.py:57-107
    _check_all_hosts_ssh_successful + cache.use_cache)."""
    import subprocess
    from concurrent.futures import ThreadPoolExecutor
    remote = [h for h in hostnames if not is_local(h)]
    if not remote:
        return []
    cache = _load_ssh_cache()
    now = time.time()

    def probe(host) -> bool:
        # BatchMode + closed stdin: a host behind password/interactive auth
        # must fail the probe immediately, not hang on a prompt
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no",
               "-o", "BatchMode=yes",
               "-o", f"ConnectTimeout={SSH_CONNECT_TIMEOUT_S}"]
        if ssh_port:
            cmd += ["-p", str(ssh_port)]
        cmd += [host, "true"]
        for _ in range(SSH_ATTEMPTS):
            try:
                if subprocess.run(cmd, capture_output=True,
                                  stdin=subprocess.DEVNULL,
                                  timeout=SSH_CONNECT_TIMEOUT_S + 5
                                  ).returncode == 0:
                    return True
            except (subprocess.TimeoutExpired, OSError):
                pass
        return False

    to_probe = [h for h in sorted(set(remote))
                if now - cache.get(_ssh_cache_key(h, ssh_port), 0)
                >= SSH_CACHE_STALENESS_S]
    bad = []
    if to_probe:
        # concurrent probes: a fleet with several dead hosts must fail in
        # one probe-timeout, not one per host (reference: launch.py:93-95
        # execute_function_multithreaded)
        with ThreadPoolExecutor(max_workers=min(32, len(to_probe))) as ex:
            for host, ok in zip(to_probe, ex.map(probe, to_probe)):
                if ok:
                    # only successes are cached, like the reference
                    cache[_ssh_cache_key(host, ssh_port)] = now
                else:
                    bad.append(host)
    _store_ssh_cache(cache, now=now)
    return bad


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvdrun-tpu",
        description="Launch a horovod_tpu distributed job")
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="number of worker processes")
    p.add_argument("-H", "--hosts", default=None,
                   help='host slots, e.g. "localhost:4,host2:4"')
    p.add_argument("--ssh-port", type=int, default=None)
    p.add_argument("-cb", "--check-build", action="store_true",
                   help="print available frameworks/controllers/features "
                        "and exit")
    p.add_argument("--config-file", default=None,
                   help="YAML runtime config; explicit CLI flags override "
                        "it, it overrides built-in defaults")
    # elastic (reference: launch.py elastic group)
    p.add_argument("--min-np", type=int, default=None)
    p.add_argument("--max-np", type=int, default=None)
    p.add_argument("--host-discovery-script", default=None,
                   help="script printing 'host:slots' lines; polled for "
                        "elastic membership changes")
    p.add_argument("--reset-limit", type=int, default=None,
                   help="max elastic resets before aborting")
    # engine knobs → env (reference: config_parser mapping)
    p.add_argument("--fusion-threshold-mb", type=float, default=None)
    p.add_argument("--cycle-time-ms", type=float, default=None)
    p.add_argument("--cache-capacity", type=int, default=None)
    p.add_argument("--timeline-filename", default=None)
    p.add_argument("--timeline-mark-cycles", action="store_true")
    p.add_argument("--stall-check-time-seconds", type=float, default=None)
    p.add_argument("--stall-shutdown-time-seconds", type=float, default=None)
    p.add_argument("--no-stall-check", action="store_true")
    p.add_argument("--hierarchical-allreduce", action="store_true",
                   help="two-level topology-aware allreduce "
                        "(HOROVOD_HIERARCHICAL_ALLREDUCE): in-jit, "
                        "reduce-scatter over the fast (ICI) mesh axes + "
                        "cross-slice allreduce + all-gather back; on the "
                        "host data plane, intra-host reduce-scatter -> "
                        "inter-host allreduce among local leaders -> "
                        "intra-host allgather (the engine groups ranks by "
                        "the HOROVOD_CROSS_RANK host index this launcher "
                        "exports per slot)")
    p.add_argument("--small-tensor-algo", choices=("star", "rd"),
                   default=None,
                   help="host data-plane route for sub-express-lane "
                        "allreduces (HOROVOD_SMALL_TENSOR_ALGO): 'star' "
                        "(rank-0 hub) or 'rd' (log2(p) recursive "
                        "doubling, no hub hotspot)")
    p.add_argument("--autotune", action="store_true",
                   help="enable online Bayesian tuning of cycle time / "
                        "fusion threshold / cache (HOROVOD_AUTOTUNE)")
    p.add_argument("--autotune-log", default=None,
                   help="CSV file recording autotune samples "
                        "(HOROVOD_AUTOTUNE_LOG)")
    p.add_argument("--autotune-warmup-samples", type=int, default=None)
    p.add_argument("--autotune-steps", type=int, default=None)
    p.add_argument("--autotune-sample-cycles", type=int, default=None)
    p.add_argument("--start-timeout", type=float, default=120.0)
    p.add_argument("--verbose", action="store_true")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command")
    return p


def _engine_env(args) -> dict:
    env = {}
    if args.fusion_threshold_mb is not None:
        env["HOROVOD_FUSION_THRESHOLD"] = str(
            int(args.fusion_threshold_mb * 1024 * 1024))
    if args.cycle_time_ms is not None:
        env["HOROVOD_CYCLE_TIME"] = str(args.cycle_time_ms)
    if args.cache_capacity is not None:
        env["HOROVOD_CACHE_CAPACITY"] = str(args.cache_capacity)
    if args.timeline_filename:
        env["HOROVOD_TIMELINE"] = args.timeline_filename
    if args.timeline_mark_cycles:
        env["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
    if args.stall_check_time_seconds is not None:
        env["HOROVOD_STALL_CHECK_TIME_SECONDS"] = str(
            args.stall_check_time_seconds)
    if args.stall_shutdown_time_seconds is not None:
        env["HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"] = str(
            args.stall_shutdown_time_seconds)
    if args.no_stall_check:
        env["HOROVOD_STALL_CHECK_DISABLE"] = "1"
    if args.hierarchical_allreduce:
        env["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
    if args.small_tensor_algo is not None:
        env["HOROVOD_SMALL_TENSOR_ALGO"] = args.small_tensor_algo
    if args.autotune:
        env["HOROVOD_AUTOTUNE"] = "1"
    if args.autotune_log:
        env["HOROVOD_AUTOTUNE_LOG"] = args.autotune_log
    if args.autotune_warmup_samples is not None:
        env["HOROVOD_AUTOTUNE_WARMUP_SAMPLES"] = \
            str(args.autotune_warmup_samples)
    if args.autotune_steps is not None:
        env["HOROVOD_AUTOTUNE_STEPS"] = str(args.autotune_steps)
    if args.autotune_sample_cycles is not None:
        env["HOROVOD_AUTOTUNE_SAMPLE_CYCLES"] = \
            str(args.autotune_sample_cycles)
    return env


def publish_assignments(kv: KVServer, slots, controller_addr: str,
                        controller_port: int, data_port: int,
                        generation: int = 0, epoch: int = 0):
    """Publish per-slot topology under a generation scope (reference:
    rendezvous GET_RANK_AND_SIZE scope, runner/elastic/rendezvous.py).
    ``epoch`` is the publishing driver's control epoch — embedded so
    workers can fence a lingering pre-crash driver's stale topology."""
    from horovod_tpu.common import kv_keys
    for s in slots:
        kv.put_json(
            kv_keys.rank_and_size(generation, s.hostname, s.local_rank),
            {"rank": s.rank, "size": s.size,
             "local_rank": s.local_rank, "local_size": s.local_size,
             "cross_rank": s.cross_rank, "cross_size": s.cross_size,
             "controller_addr": controller_addr,
             "controller_port": controller_port,
             "controller_data_port": data_port,
             "epoch": epoch}, epoch=epoch)
    kv.put_json(kv_keys.generation(),
                {"generation": generation, "epoch": epoch},
                epoch=epoch)


def launcher_addr(hostnames) -> str:
    """Address workers use to reach the launcher's rendezvous KV server.

    The KV server runs in the *launcher* process — not on the first slot's
    host — so multi-host jobs must be given the launcher's reachable address,
    not the controller's. Resolved via the UDP-connect trick toward a worker
    host (reference: the driver-service NIC probe picks a routable interface,
    runner/driver/driver_service.py:162-258 — getfqdn() is often
    unresolvable or loopback-mapped from remote hosts)."""
    remote = [h for h in hostnames if h not in ("localhost", "127.0.0.1")]
    if not remote:
        return "127.0.0.1"
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((remote[0], 9))  # no traffic sent; just routes
        return s.getsockname()[0]
    except OSError:
        return socket.getfqdn()
    finally:
        s.close()


def worker_env(slot, controller_addr, controller_port, data_port,
               kv_port, extra, elastic=False, generation=0,
               rendezvous_addr=None, epoch=0) -> dict:
    env = slot.to_env()
    env.update(extra)
    env.update({
        "HOROVOD_CONTROLLER_ADDR": controller_addr,
        "HOROVOD_CONTROLLER_PORT": str(controller_port),
        "HOROVOD_CONTROLLER_DATA_PORT": str(data_port),
        "HOROVOD_RENDEZVOUS_ADDR": rendezvous_addr or controller_addr,
        "HOROVOD_RENDEZVOUS_PORT": str(kv_port),
    })
    if elastic:
        env["HOROVOD_ELASTIC"] = "1"
        env["HOROVOD_ELASTIC_GENERATION"] = str(generation)
        env["HOROVOD_CONTROL_EPOCH"] = str(epoch)
    # replicated control plane: hand workers the full replica endpoint
    # list so their KV clients fail over instead of pinning one endpoint
    from horovod_tpu.common.env_registry import env_str
    replica_eps = env_str("HOROVOD_KV_REPLICA_ENDPOINTS")
    if replica_eps:
        env["HOROVOD_KV_REPLICA_ENDPOINTS"] = replica_eps
    # Workers must not grab a single-tenant accelerator relay the launcher
    # process may own; training scripts opt in explicitly.
    env.setdefault("JAX_PLATFORMS", os.environ.get("JAX_PLATFORMS", "cpu"))
    return env


def run_static(args, liveness_check=None, kv=None) -> int:
    """``kv``: optionally a caller-owned (started) KVServer — the caller
    reads worker-published keys (task results) after this returns, and
    owns stop()."""
    host_string = args.hosts or f"localhost:{args.num_proc}"
    host_list = hosts_lib.parse_hosts(host_string)
    np_ = args.num_proc or sum(h.slots for h in host_list)
    slots = hosts_lib.get_host_assignments(host_list, np_)

    bad = check_hosts_ssh({s.hostname for s in slots},
                          getattr(args, "ssh_port", None))
    if bad:
        sys.stderr.write(
            f"[launcher] hosts not ssh-reachable: {', '.join(bad)}\n")
        return 1

    controller_addr = slots[0].hostname if slots[0].hostname != "localhost" \
        else "127.0.0.1"
    controller_port, data_port = free_ports(2)
    own_kv = kv is None
    if own_kv:
        kv = KVServer().start()
    try:
        publish_assignments(kv, slots, controller_addr, controller_port,
                            data_port)
        extra = _engine_env(args)
        rdv_addr = launcher_addr([s.hostname for s in slots])
        workers: List[WorkerProcess] = []
        for s in slots:
            env = worker_env(s, controller_addr, controller_port, data_port,
                             kv.port, extra, rendezvous_addr=rdv_addr)
            workers.append(WorkerProcess(s.hostname, s.rank, args.command,
                                         env))
        return _wait_all(workers, liveness_check)
    finally:
        if own_kv:
            kv.stop()


def _terminate_all(workers):
    """SIGTERM + bounded wait, escalating to SIGKILL for processes that
    trap the signal — the abort paths must return, not raise."""
    workers = list(workers)
    for w in workers:
        w.terminate()
    for w in workers:
        try:
            w.wait(timeout=10)
        except Exception:  # noqa: BLE001 — TimeoutExpired etc.
            w.kill()


def _wait_all(workers: List[WorkerProcess], liveness_check=None) -> int:
    """Fail fast: first non-zero exit kills the rest (reference:
    gloo_run terminate-on-failure). ``liveness_check()`` (if given) runs
    every poll; a non-None error string aborts the job — the programmatic
    run() uses it to enforce start_timeout."""
    rc = 0
    pending = {w.rank: w for w in workers}
    try:
        while pending:
            if liveness_check is not None:
                err = liveness_check()
                if err is not None:
                    sys.stderr.write(f"[launcher] {err}; terminating job\n")
                    _terminate_all(pending.values())
                    return 1
            for rank, w in list(pending.items()):
                code = w.poll()
                if code is None:
                    continue
                del pending[rank]
                if code != 0:
                    sys.stderr.write(
                        f"[launcher] worker rank {rank} on {w.hostname} "
                        f"exited with code {code}; terminating job\n")
                    rc = code
                    _terminate_all(pending.values())
                    return rc
            time.sleep(0.1)
    except KeyboardInterrupt:
        for w in pending.values():
            w.terminate()
        rc = 130
    return rc


def run_elastic(args) -> int:
    from horovod_tpu.common.env_registry import env_bool, env_str
    # Durable control plane: with HOROVOD_KV_DIR set the driver runs as a
    # supervised subprocess — a crashed/killed driver is respawned and
    # rehydrates from the WAL while workers keep training headless.
    if env_str("HOROVOD_KV_DIR") and env_bool("HOROVOD_DRIVER_SUPERVISE"):
        from horovod_tpu.runner.elastic.supervisor import run_supervised
        return run_supervised(args)
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.elastic.discovery import HostDiscoveryScript
    min_np = args.min_np or args.num_proc
    max_np = args.max_np or args.num_proc or min_np
    discovery = HostDiscoveryScript(args.host_discovery_script)
    driver = ElasticDriver(
        discovery=discovery, min_np=min_np, max_np=max_np,
        command=args.command, extra_env=_engine_env(args),
        reset_limit=args.reset_limit, verbose=args.verbose)
    return driver.run(start_timeout=args.start_timeout)


def run_commandline(argv: Optional[List[str]] = None) -> int:
    # Launcher-side logging honors the same HOROVOD_LOG_LEVEL /
    # HOROVOD_LOG_TIMESTAMP knobs as the engine and workers (satellite:
    # one knob set for the whole stack — table in docs/DESIGN.md).
    from horovod_tpu.common.hvd_logging import setup_python_logging
    setup_python_logging()
    parser = make_parser()
    args = parser.parse_args(argv)
    if args.check_build:
        print(check_build(args.verbose))
        return 0
    if args.config_file:
        # re-parse with the file folded into defaults: CLI flags win over
        # the file, the file wins over built-in defaults
        apply_config_file(parser, args.config_file)
        args = parser.parse_args(argv)
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if not args.command:
        make_parser().error("no training command given")
    elastic = args.host_discovery_script is not None or \
        (args.min_np is not None or args.max_np is not None)
    if elastic and not args.host_discovery_script:
        make_parser().error("elastic mode requires --host-discovery-script")
    if not elastic and not (args.num_proc or args.hosts):
        make_parser().error("specify -np and/or -H")
    return run_elastic(args) if elastic else run_static(args)


def main():
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
