"""Leader-lease replicated control-plane KV (ISSUE 19).

The PR-10 durable KV survives driver *restarts* but still dies with its
host — the one machine whose loss takes down rendezvous, elastic resize,
autoscaling, tuning publication, and serve discovery at once. This
module replicates it: N :class:`ReplicaKVServer` processes (indexed by
``replica_id`` into a shared endpoint list) run the same sharded-WAL
store, with

- **one leader holding a time-bounded lease** — granted by a follower-
  majority election, persisted as a ``lease`` record in the WAL, and
  extended only by majority-acked append rounds. A leader that cannot
  reach a majority lets its lease lapse and steps down; followers wait
  1.5 leases of silence before electing, so (under bounded clock drift)
  two replicas never both believe they hold the lease at one instant.
- **synchronous majority replication** — client mutations are accepted
  only by the leader, appended to its WAL, forwarded to every follower,
  and acked to the client only once a majority (leader included) holds
  them. Every envelope carries the control epoch as the replication
  term: a deposed leader's in-flight forwards are 409ed by followers
  that have seen a newer term, and the deposed leader **self-fences**
  (steps down) on the first majority-refused write.
- **highest-(epoch, last-term, WAL-length) elections** — every WAL
  record is stamped with the replication term it was appended under,
  and the vote-grant rule (shared with the ``ReplicaSpec`` model via
  ``horovod_tpu/verify/rules.py``) refuses any candidate whose
  ``(last-record term, length)`` is behind the voter's — the Raft
  up-to-date ordering, under which a majority-committed (acked) write
  can never be missing from a newly elected leader. Grants are
  **persisted** (``vote`` file) before they are sent, so a replica the
  supervisor respawns mid-election cannot vote twice in one epoch.
  Winning bumps the epoch.
- **WAL-divergence repair** — every append envelope carries the
  previous record's ``(seq, term)`` and a follower matching on either
  dimension failing answers "resync me" (Raft log matching — bare
  sequence numbers cannot see two equal-length logs that diverged
  across a failover). The diverged follower is resynced from the
  leader's full state; its un-committed suffix is truncated with a
  loud tripwire log, and its shard WALs are rewritten to the committed
  prefix.

The elastic driver talks to the replica set through
:class:`ReplicatedKVHandle` — the same accessor surface as an in-process
``KVServer`` (``put_json``/``get_json``/``delete``/``delete_prefix``/
``keys``/``epoch``/``recovered``), backed by a failover-aware
:class:`~horovod_tpu.runner.http_kv.KVClient`. At attach it bumps the
control epoch (fencing any predecessor driver incarnation) and records
its ownership under the ``control_epoch`` key; when an *election* bumps
the epoch underneath it, the handle distinguishes "deposed by a rival
driver" (stand down, :class:`StaleEpochError`) from "same driver, new
KV term" (adopt and continue) by checking that ownership record —
read through the *leader*, never a possibly-lagging follower.

Run one replica as a subprocess::

    python -m horovod_tpu.runner.replica_kv \
        --id 0 --endpoints host:7001,host:7002,host:7003 --dir /kv/r0
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple
from urllib import error as urlerror
from urllib import request as urlrequest

from horovod_tpu.common import journal, kv_keys
from horovod_tpu.runner.http_kv import (LEADER_HEADER, KVClient, KVServer,
                                        StaleEpochError)

_MAX_VOTE_MEMORY = 64     # per-epoch vote records retained
_RESYNC_TOKEN_WINDOW = 1024


def _rules():
    """The shared election/quorum rules (lazy: the verify package pulls
    in the spec suite, which a replica subprocess shouldn't pay for at
    import time)."""
    from horovod_tpu.verify import rules
    return rules


def _logger():
    from horovod_tpu.common.hvd_logging import get_logger
    return get_logger("runner.replica_kv")


class ReplicaKVServer(KVServer):
    """One member of a replicated KV set. See the module docstring for
    the protocol; this class adds the replica roles on top of the base
    server's sharded-WAL store via the ``_route`` handler hook."""

    # a restarting replica must NOT outrun its leader's term — epoch
    # bumps come from elections and driver attach, never from restarts
    _bump_epoch_on_start = False

    def __init__(self, replica_id: int, endpoints: List[str],
                 kv_dir: str, port: Optional[int] = None,
                 lease_seconds: Optional[float] = None,
                 snapshot_bytes: Optional[int] = None):
        assert kv_dir, "a KV replica is always durable (kv_dir required)"
        self.replica_id = int(replica_id)
        self._endpoints = [str(e).strip() for e in endpoints]
        assert 0 <= self.replica_id < len(self._endpoints)
        if lease_seconds is None:
            from horovod_tpu.common.env_registry import env_float
            lease_seconds = env_float("HOROVOD_KV_LEASE_SECONDS")
        self._lease = float(lease_seconds)
        now = time.monotonic()
        self._role = "follower"
        self._leader_id: Optional[int] = None
        self._leader_seen = now
        self._lease_until = 0.0     # leader: lease valid until
        self._lease_grant_t = 0.0   # leader: last majority extension
        self._commit = 0            # highest majority-committed seq
        self._last_term = 0         # term ("t") of the last WAL record
        self._votes_cast: Dict[int, int] = {}   # epoch -> candidate id
        self._vote_floor = 0     # highest epoch ever granted (persisted)
        self._next_proposal = 0  # grows per attempt so split votes resolve
        self._peer_seen: Dict[int, float] = {}  # id -> last good contact
        # staggered bootstrap/election timers: replica 0 usually wins the
        # first election, and retries never synchronize
        self._elect_after = now + self._lease * (1.5 + 0.5 * self.replica_id
                                                 + 0.3 * random.random())
        self._stop_evt = threading.Event()
        self._ticker: Optional[threading.Thread] = None
        if port is None:
            port = int(self._endpoints[self.replica_id].rsplit(":", 1)[1])
        super().__init__(port=port, kv_dir=kv_dir,
                         snapshot_bytes=snapshot_bytes)
        # everything replayed from our own WAL is only *locally* durable
        # — it may be an un-majority-committed suffix — so the commit
        # point stays 0 and is re-learned from append/heartbeat rounds
        # (or, as leader, from the first majority-acked append). The
        # last record's replication term IS restored: it is this
        # replica's position in the Raft log-matching order.
        self._last_term = self._wal.last_term
        # a voter that forgets a granted vote across a respawn could
        # grant the same epoch twice (two leaders, one term) — reload
        # the durable grant and never vote at or below it differently
        self._vote_floor, voted_for = self._wal.load_vote()
        if voted_for is not None:
            self._votes_cast[self._vote_floor] = voted_for

    # -- lifecycle ------------------------------------------------------------

    def start(self):
        super().start()
        self._ticker = threading.Thread(target=self._tick_loop, daemon=True)
        self._ticker.start()
        return self

    def stop(self):
        self._stop_evt.set()
        if self._ticker is not None:
            self._ticker.join(timeout=5)
        super().stop()

    # -- HTTP routing (the base server's extension hook) ----------------------

    def _route(self, handler, method: str) -> bool:
        path, _, _ = handler.path.partition("?")
        if method == "POST" and path == "/_replica/append":
            self._h_append(handler)
            return True
        if method == "POST" and path == "/_replica/vote":
            self._h_vote(handler)
            return True
        if method == "POST" and path == "/_replica/resync":
            self._h_resync(handler)
            return True
        if method == "GET" and path == "/_replica/read":
            self._h_leader_read(handler)
            return True
        if method in ("PUT", "DELETE"):
            self._h_client_mutation(handler, method)
            return True
        return False  # plain reads (incl. /replica_status, /_kv): base

    @staticmethod
    def _read_doc(handler) -> dict:
        length = int(handler.headers.get("Content-Length", 0))
        raw = handler.rfile.read(length)
        try:
            doc = json.loads(raw) if raw else {}
        except ValueError:
            doc = {}
        return doc if isinstance(doc, dict) else {}

    # -- client-facing mutations ----------------------------------------------

    def _h_client_mutation(self, handler, method: str):
        from urllib import parse as urlparse
        path, _, query = handler.path.partition("?")
        body = b""
        if method == "PUT":
            length = int(handler.headers.get("Content-Length", 0))
            body = handler.rfile.read(length)
        with self._lock:
            is_leader = self._role == "leader" and \
                time.monotonic() < self._lease_until
        if not is_leader:
            self._send_not_leader(handler)
            return
        if method == "PUT":
            op = {"op": "put", "k": path.lstrip("/"),
                  "v": base64.b64encode(body).decode()}
        elif path == "/_kv/prefix":
            q = urlparse.parse_qs(query)
            op = {"op": "delp", "p": q.get("p", [""])[0]}
        else:
            op = {"op": "del", "k": path.lstrip("/")}
        try:
            outcome, existed = self._replicate(op, handler._claimed_epoch(),
                                               handler._token())
        except StaleEpochError as e:
            handler._send_fenced(e)
            return
        if outcome == "ok":
            if method == "DELETE" and op["op"] == "del" and not existed:
                handler.send_response(404)
            else:
                handler.send_response(200)
            handler.end_headers()
        elif outcome == "not_leader":
            self._send_not_leader(handler)
        else:  # lost leadership mid-write: never acked, client retries
            handler._send_json({"error": "no_leader"}, status=503)

    def _h_leader_read(self, handler):
        """Leader-only read (``GET /_replica/read?k=...``): 307/503 from
        anyone not holding a live lease. Plain GETs are served from
        whichever replica the client hit — fine for rendezvous polling,
        but a read that *decides* something (the driver's post-fence
        ownership check) must not see a lagging follower's stale state."""
        from urllib import parse as urlparse
        _, _, query = handler.path.partition("?")
        key = urlparse.parse_qs(query).get("k", [""])[0]
        with self._lock:
            is_leader = self._role == "leader" and \
                time.monotonic() < self._lease_until
            val = self._store.get(key) if is_leader else None
            epoch = self.epoch
        if not is_leader:
            self._send_not_leader(handler)
            return
        handler._send_json(
            {"found": val is not None, "epoch": epoch,
             "v": base64.b64encode(val).decode()
             if val is not None else None})

    def _send_not_leader(self, handler):
        with self._lock:
            lid = self._leader_id
            fresh = (time.monotonic() - self._leader_seen) < self._lease * 2
        if lid is not None and lid != self.replica_id and fresh:
            ep = self._endpoints[lid]
            handler.send_response(307)
            handler.send_header("Location", f"http://{ep}{handler.path}")
            handler.send_header(LEADER_HEADER, ep)
            handler.send_header("Content-Length", "0")
            handler.end_headers()
        else:
            handler._send_json({"error": "no_leader"}, status=503)

    # -- leader write path ----------------------------------------------------

    def _replicate(self, op: dict, epoch_claim: Optional[int],
                   token: Optional[Tuple[str, int]]) \
            -> Tuple[str, bool]:
        """Append ``op`` through the replication pipeline: local WAL →
        synchronous forward → majority ack → commit. Returns
        ``(outcome, existed)``; raises StaleEpochError for a fenced
        client claim. Holding the lock across the forward serializes
        writes — correct first, fast enough for a control plane."""
        rules = _rules()
        with self._lock:
            now = time.monotonic()
            if self._role != "leader" or now >= self._lease_until:
                return "not_leader", False
            try:
                self._check_epoch_locked(epoch_claim)  # may adopt newer
            except StaleEpochError as e:
                self._log_stale(e)
                raise
            if self._dedup_locked(token):
                return "ok", True  # retry of a committed op: applied once
            prev = self._seq
            prev_term = self._last_term
            self._seq += 1
            # "t" is the replication term this record was appended
            # under — the Raft log-matching stamp. Without it two
            # equal-length logs that diverged across a failover (a
            # deposed leader's un-acked suffix vs the successor's
            # committed one) are indistinguishable by seq alone.
            rec = dict(op, s=self._seq, t=self.epoch)
            if epoch_claim is not None:
                rec["e"] = int(epoch_claim)
            if token is not None:
                rec["c"], rec["n"] = token[0], int(token[1])
            existed = self._apply_record_locked(rec)
            env = {"term": self.epoch, "leader": self.replica_id,
                   "prev": prev, "prev_term": prev_term,
                   "ops": [rec], "commit": self._commit}
            acks, resync_peers, deposed_by = self._send_round_locked(env)
            if deposed_by is not None:
                self._step_down_locked(
                    f"majority-refused write (newer term {deposed_by})")
                return "lost", existed
            if acks >= rules.majority(len(self._endpoints)):
                self._commit = rec["s"]
                self._lease_until = now + self._lease
                self._lease_grant_t = now
                outcome = "ok"
            else:
                self._step_down_locked(
                    "write could not reach a follower majority")
                outcome = "lost"
        for pid in resync_peers:
            self._resync_peer(pid)
        return outcome, existed

    def _apply_record_locked(self, rec: dict) -> bool:
        """Apply one replicated record: store mutation + WAL append +
        dedupe-token registration. Caller holds the lock."""
        kind = rec.get("op")
        existed = True
        if kind == "put":
            self._store[rec["k"]] = base64.b64decode(rec["v"])
        elif kind == "del":
            existed = self._store.pop(rec["k"], None) is not None
        elif kind == "delp":
            for k in [k for k in self._store
                      if k.startswith(rec.get("p", ""))]:
                del self._store[k]
        # "lease" records mutate nothing: they are the persisted grant
        if rec.get("c") is not None and rec.get("n") is not None:
            self._applied[(rec["c"], int(rec["n"]))] = True
        if isinstance(rec.get("s"), int):
            self._seq = max(self._seq, rec["s"])
        if isinstance(rec.get("t"), int):
            self._last_term = rec["t"]
        if self._wal is not None:
            self._wal.append(rec, self._store)
            self._export_metrics()
        return existed

    def _send_round_locked(self, env: dict) \
            -> Tuple[int, List[int], Optional[int]]:
        """One append round to every peer: ``(acks_including_self,
        peers_needing_resync, deposing_term_or_None)``."""
        acks = 1  # self
        resync_peers: List[int] = []
        deposed_by: Optional[int] = None
        now = time.monotonic()
        for pid, resp in self._broadcast("/_replica/append", env,
                                         timeout=max(0.2, self._lease / 2)):
            if resp is None:
                continue
            if resp.get("fenced"):
                term = int(resp.get("term", self.epoch + 1))
                self._adopt_term_locked(max(term, self.epoch))
                deposed_by = term
                continue
            if resp.get("resync"):
                self._peer_seen[pid] = now
                resync_peers.append(pid)
                continue
            if resp.get("ok"):
                self._peer_seen[pid] = now
                acks += 1
        return acks, resync_peers, deposed_by

    def _step_down_locked(self, why: str):
        if self._role == "leader":
            _logger().warning(
                "kv-replica %d: self-fencing (stepping down): %s",
                self.replica_id, why)
            journal.emit("replica_kv", "self_fence",
                         control_epoch=self.epoch,
                         replica=self.replica_id, why=why)
        self._role = "follower"
        self._leader_id = None
        self._lease_until = 0.0
        self._elect_after = time.monotonic() + self._lease * (
            1.5 + 0.5 * self.replica_id + 0.3 * random.random())

    def _adopt_term_locked(self, term: int):
        if term > self.epoch:
            self.epoch = int(term)
            if self._wal is not None:
                self._wal.store_epoch(self.epoch)

    # -- follower: replicated append ------------------------------------------

    def _h_append(self, handler):
        doc = self._read_doc(handler)
        term = int(doc.get("term", -1))
        now = time.monotonic()
        with self._lock:
            if term < self.epoch:
                # a deposed leader's in-flight forward: 409 everywhere
                handler._send_json({"fenced": True, "term": self.epoch},
                                   status=409)
                return
            self._adopt_term_locked(term)
            if self._role != "follower":
                self._step_down_locked(
                    f"append from leader {doc.get('leader')} at term {term}")
            self._role = "follower"
            self._leader_id = int(doc.get("leader", -1))
            self._leader_seen = now
            # Raft log matching: the append lands only when BOTH the
            # previous index and its term agree. Index alone cannot see
            # an equal-length diverged log (a deposed leader that kept
            # a never-majority-acked record at the same seq the new
            # leader committed a different one) — term mismatch at the
            # same seq is exactly that split, and it must resync.
            if int(doc.get("prev", -1)) != self._seq or \
                    int(doc.get("prev_term", -1)) != self._last_term:
                handler._send_json({"ok": False, "resync": True,
                                    "have": self._seq,
                                    "have_term": self._last_term})
                return
            for rec in doc.get("ops", []):
                self._apply_record_locked(rec)
            self._commit = max(self._commit, int(doc.get("commit", 0)))
            handler._send_json({"ok": True, "seq": self._seq})

    # -- votes ----------------------------------------------------------------

    def _h_vote(self, handler):
        rules = _rules()
        doc = self._read_doc(handler)
        cand = int(doc.get("cand", -1))
        cand_epoch = int(doc.get("epoch", -1))
        cand_len = int(doc.get("len", -1))
        cand_term = int(doc.get("last_term", -1))
        now = time.monotonic()
        with self._lock:
            heard = self._leader_id is not None and \
                (now - self._leader_seen) < self._lease * 1.5
            if self._role == "leader" and now < self._lease_until:
                heard = True  # we ARE the fresh leaseholder
            granted = rules.vote_grants(
                self.epoch, self._last_term, self._seq,
                cand_epoch, cand_term, cand_len, heard) and \
                cand_epoch >= self._vote_floor and \
                self._votes_cast.get(cand_epoch, cand) == cand
            if granted:
                # the grant is durable BEFORE it is sent: a voter the
                # supervisor respawns mid-election must refuse a second
                # candidate at any epoch it already voted in. Persist
                # failure = no grant.
                granted = self._wal.store_vote(cand_epoch, cand)
            if granted:
                self._vote_floor = cand_epoch
                self._votes_cast[cand_epoch] = cand
                while len(self._votes_cast) > _MAX_VOTE_MEMORY:
                    self._votes_cast.pop(min(self._votes_cast))
            handler._send_json({"granted": bool(granted),
                                "term": self.epoch, "len": self._seq,
                                "last_term": self._last_term})

    def _run_election(self):
        rules = _rules()
        now = time.monotonic()
        with self._lock:
            if self._role == "leader":
                return
            # each attempt proposes a strictly higher epoch than any
            # prior one — otherwise two candidates that split a vote at
            # epoch+1 have both burned their one vote there and no
            # election at that epoch can ever reach a majority. The
            # persisted vote floor joins the max: a respawned candidate
            # must not self-vote in an epoch it already granted away.
            proposed = max(self.epoch + 1, self._next_proposal,
                           self._vote_floor + 1)
            self._next_proposal = proposed + 1
            my_len = self._seq
            my_term = self._last_term
            # the self-vote is durable like any other grant
            if not self._wal.store_vote(proposed, self.replica_id):
                return
            self._vote_floor = proposed
            self._votes_cast[proposed] = self.replica_id
        votes = 1
        for _pid, resp in self._broadcast(
                "/_replica/vote",
                {"cand": self.replica_id, "epoch": proposed,
                 "len": my_len, "last_term": my_term},
                timeout=max(0.2, self._lease / 2)):
            if resp is None:
                continue
            if resp.get("granted"):
                votes += 1
            elif int(resp.get("term", 0)) > proposed:
                with self._lock:
                    self._adopt_term_locked(int(resp["term"]))
                return
        won = False
        with self._lock:
            if self.epoch >= proposed:
                return  # superseded while soliciting
            if votes >= rules.majority(len(self._endpoints)):
                self._adopt_term_locked(proposed)
                self._role = "leader"
                self._leader_id = self.replica_id
                self._lease_until = now + self._lease
                self._lease_grant_t = now
                won = True
            else:
                self._elect_after = now + self._lease * (
                    0.5 + 0.5 * self.replica_id + random.random())
        if won:
            _logger().warning(
                "kv-replica %d: elected leader (epoch %d, wal seq %d, "
                "%d/%d votes)", self.replica_id, proposed, my_len, votes,
                len(self._endpoints))
            journal.emit("replica_kv", "elected_leader",
                         control_epoch=proposed, replica=self.replica_id,
                         wal_seq=my_len, votes=votes,
                         replicas=len(self._endpoints))
            # persist + replicate the lease grant; failing to establish
            # it with a majority immediately self-fences
            self._replicate({"op": "lease", "leader": self.replica_id,
                             "dur": self._lease}, self.epoch, None)

    # -- resync (WAL-divergence repair) ---------------------------------------

    def _resync_peer(self, pid: int):
        with self._lock:
            doc = {"term": self.epoch, "leader": self.replica_id,
                   "seq": self._seq, "last_term": self._last_term,
                   "commit": self._commit,
                   "store": {k: base64.b64encode(v).decode()
                             for k, v in self._store.items()},
                   "tokens": [list(t) for t in
                              list(self._applied)[-_RESYNC_TOKEN_WINDOW:]]}
        self._post_json(self._endpoints[pid], "/_replica/resync", doc,
                        timeout=max(1.0, self._lease))

    def _h_resync(self, handler):
        doc = self._read_doc(handler)
        term = int(doc.get("term", -1))
        now = time.monotonic()
        with self._lock:
            if term < self.epoch:
                handler._send_json({"fenced": True, "term": self.epoch},
                                   status=409)
                return
            new_store = {k: base64.b64decode(v)
                         for k, v in doc.get("store", {}).items()}
            leader_seq = int(doc.get("seq", 0))
            diverged = sorted(
                k for k, v in self._store.items()
                if new_store.get(k) != v)
            if self._seq > leader_seq or diverged:
                # TRIPWIRE: this follower accepted records that never
                # reached a majority — truncate them to the committed
                # prefix, loudly. Anything acked to a client is in the
                # leader's state by the election rule, so nothing acked
                # is lost here.
                _logger().warning(
                    "kv-replica %d: WAL DIVERGENCE REPAIR on rejoin: "
                    "truncating un-majority-committed suffix (local seq "
                    "%d > leader seq %d; %d diverged key(s): %s)",
                    self.replica_id, self._seq, leader_seq,
                    len(diverged), diverged[:8])
                journal.emit("replica_kv", "divergence_repair",
                             control_epoch=term,
                             replica=self.replica_id,
                             local_seq=self._seq, leader_seq=leader_seq,
                             diverged=len(diverged))
            elif self._seq < leader_seq:
                _logger().info(
                    "kv-replica %d: catching up from leader %s "
                    "(local seq %d -> %d)", self.replica_id,
                    doc.get("leader"), self._seq, leader_seq)
            self._adopt_term_locked(term)
            self._store = new_store
            self._seq = leader_seq
            self._last_term = int(doc.get("last_term", 0))
            self._commit = int(doc.get("commit", 0))
            self._applied = {}
            for tok in doc.get("tokens", []):
                try:
                    self._applied[(str(tok[0]), int(tok[1]))] = True
                except (TypeError, ValueError, IndexError):
                    pass
            if self._wal is not None:
                self._wal.max_seq = self._seq
                self._wal.last_term = self._last_term
                self._wal.compact_all(self._store)
                self._export_metrics()
            self._role = "follower"
            self._leader_id = int(doc.get("leader", -1))
            self._leader_seen = now
            handler._send_json({"ok": True, "seq": self._seq})

    # -- lease ticker ----------------------------------------------------------

    def _tick_loop(self):
        period = max(0.05, self._lease / 4)
        while not self._stop_evt.wait(period):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — the ticker must survive
                pass

    def _tick(self):
        now = time.monotonic()
        with self._lock:
            role = self._role
            silent = now - self._leader_seen
            elect_due = now >= self._elect_after
        if role == "leader":
            self._heartbeat()
        elif elect_due and silent > self._lease * 1.5:
            self._run_election()

    def _heartbeat(self):
        """Leader lease extension: an empty majority-acked append round.
        Doubles as the follower resync trigger (prev-seq mismatch)."""
        rules = _rules()
        resync_peers: List[int] = []
        with self._lock:
            if self._role != "leader":
                return
            now = time.monotonic()
            env = {"term": self.epoch, "leader": self.replica_id,
                   "prev": self._seq, "prev_term": self._last_term,
                   "ops": [], "commit": self._commit}
            acks, resync_peers, deposed_by = self._send_round_locked(env)
            if deposed_by is not None:
                self._step_down_locked(
                    f"heartbeat refused (newer term {deposed_by})")
            elif acks >= rules.majority(len(self._endpoints)):
                self._lease_until = now + self._lease
                self._lease_grant_t = now
            elif now >= self._lease_until:
                self._step_down_locked(
                    "lease expired without a follower majority")
        for pid in resync_peers:
            self._resync_peer(pid)

    # -- peer transport --------------------------------------------------------

    def _post_json(self, endpoint: str, path: str, doc: dict,
                   timeout: float) -> Optional[dict]:
        req = urlrequest.Request(
            f"http://{endpoint}{path}", data=json.dumps(doc).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        try:
            with urlrequest.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read())
        except urlerror.HTTPError as e:
            try:
                return json.loads(e.read())
            except ValueError:
                return None
        except (urlerror.URLError, ConnectionError, OSError, ValueError):
            return None

    def _broadcast(self, path: str, doc: dict, timeout: float) \
            -> List[Tuple[int, Optional[dict]]]:
        """POST to every peer in parallel; collect (peer_id, response)."""
        peers = [(i, ep) for i, ep in enumerate(self._endpoints)
                 if i != self.replica_id]
        if not peers:
            return []
        results: List[Tuple[int, Optional[dict]]] = []
        lock = threading.Lock()

        def one(pid, ep):
            resp = self._post_json(ep, path, doc, timeout)
            with lock:
                results.append((pid, resp))

        threads = [threading.Thread(target=one, args=p, daemon=True)
                   for p in peers]
        for t in threads:
            t.start()
        deadline = time.monotonic() + timeout + 0.5
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        with lock:
            return list(results)

    # -- status ----------------------------------------------------------------

    def _replica_status(self) -> dict:
        now = time.monotonic()
        with self._lock:
            if self._role == "leader":
                lease_age = now - self._lease_grant_t
                leader = self.replica_id
            else:
                lease_age = now - self._leader_seen
                leader = self._leader_id
            return {"id": self.replica_id, "role": self._role,
                    "leader": leader, "epoch": self.epoch,
                    "seq": self._seq, "last_term": self._last_term,
                    "commit": self._commit,
                    "lease_age": round(lease_age, 3),
                    "lease_seconds": self._lease,
                    "replicas": len(self._endpoints),
                    "endpoints": self._endpoints,
                    "peers": {str(pid): round(now - t, 3)
                              for pid, t in self._peer_seen.items()},
                    "shards": (self._wal.shard_bytes()
                               if self._wal is not None else {}),
                    "store_hash": self._store_hash_locked()}


# ===========================================================================
# replica-set helpers (supervisor + chaos harness)
# ===========================================================================

def replica_dir(base_dir: str, replica_id: int) -> str:
    return os.path.join(base_dir, f"replica{int(replica_id)}")


def die_with_parent():
    """``preexec_fn`` asking the kernel to SIGTERM this child when its
    parent dies (Linux ``PR_SET_PDEATHSIG``). A SIGKILLed supervisor
    never runs its cleanup path — without this its replica fleet (and
    driver) would outlive it as orphans holding inherited pipes open.
    Best-effort no-op elsewhere."""
    try:
        import ctypes
        libc = ctypes.CDLL(None, use_errno=True)
        libc.prctl(1, signal.SIGTERM)  # PR_SET_PDEATHSIG = 1
    except Exception:  # noqa: BLE001 — portability fallback, not a gate
        pass


def spawn_replica(replica_id: int, endpoints: List[str], base_dir: str,
                  lease_seconds: Optional[float] = None,
                  env: Optional[dict] = None) -> subprocess.Popen:
    """Launch one replica as a subprocess (the supervisor's — and the
    chaos harness's — unit of kill/respawn)."""
    cmd = [sys.executable, "-m", "horovod_tpu.runner.replica_kv",
           "--id", str(int(replica_id)),
           "--endpoints", ",".join(endpoints),
           "--dir", replica_dir(base_dir, replica_id)]
    if lease_seconds is not None:
        cmd += ["--lease", str(float(lease_seconds))]
    return subprocess.Popen(cmd, env=dict(env or os.environ),
                            preexec_fn=die_with_parent)


def wait_for_leader(endpoints: List[str], timeout: float = 30.0,
                    poll: float = 0.1) -> Optional[dict]:
    """Poll ``/replica_status`` across the set until some replica reports
    itself leader. Returns its status doc (None on timeout)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for ep in endpoints:
            try:
                with urlrequest.urlopen(f"http://{ep}/replica_status",
                                        timeout=1.0) as resp:
                    st = json.loads(resp.read())
                if st.get("role") == "leader":
                    st["endpoint"] = ep
                    return st
            except (urlerror.URLError, ConnectionError, OSError,
                    ValueError):
                continue
        time.sleep(poll)
    return None


def replica_statuses(endpoints: List[str], timeout: float = 1.0) \
        -> Dict[str, Optional[dict]]:
    """One best-effort ``/replica_status`` probe per endpoint."""
    out: Dict[str, Optional[dict]] = {}
    for ep in endpoints:
        try:
            with urlrequest.urlopen(f"http://{ep}/replica_status",
                                    timeout=timeout) as resp:
                out[ep] = json.loads(resp.read())
        except (urlerror.URLError, ConnectionError, OSError, ValueError):
            out[ep] = None
    return out


# ===========================================================================
# driver-side handle
# ===========================================================================

class ReplicatedKVHandle:
    """The elastic driver's view of the replica set: the in-process
    ``KVServer`` accessor surface over a failover-aware client.

    Attach semantics (the PR-10 incarnation bump, relocated): the handle
    waits for a leader, claims ``leader_epoch + 1`` (fencing any
    lingering predecessor driver everywhere, via replication), and
    records ``{"epoch", "owner"}`` under the ``control_epoch`` key. When
    a later write is fenced, the handle re-reads that record: same owner
    means the epoch advanced by a KV *election* — adopt the new epoch
    and retry once; a different owner means a rival driver incarnation
    took over — stand down (StaleEpochError propagates, exactly the
    PR-10 contract)."""

    def __init__(self, endpoints: List[str],
                 epoch_adopted=None):
        eps = [str(e).strip() for e in endpoints if str(e).strip()]
        assert eps, "replica endpoint list is empty"
        self._endpoints = eps
        host, _, port = eps[0].rpartition(":")
        self.port = int(port)
        self.host = host
        self._client = KVClient(host, self.port, endpoints=eps)
        self.epoch = 0
        self.recovered = False
        self._incarnation = uuid.uuid4().hex
        self._on_epoch_adopted = epoch_adopted  # callback(new_epoch)

    # KVServer-surface compatibility -----------------------------------------

    def start(self, timeout: float = 60.0):
        st = wait_for_leader(self._endpoints, timeout=timeout)
        if st is None:
            raise TimeoutError(
                f"no KV leader reachable among {self._endpoints} "
                f"within {timeout:.0f}s")
        self.epoch = int(st["epoch"]) + 1
        self._client.epoch = self.epoch
        try:
            self.recovered = bool(self._client.keys(""))
        except Exception:  # noqa: BLE001 — recovery probe is advisory
            self.recovered = False
        self._client.put_json(
            kv_keys.control_epoch(),
            {"epoch": self.epoch, "owner": self._incarnation},
            attempts=6, deadline=timeout)
        return self

    def stop(self):
        pass  # the replica set outlives any one driver

    @property
    def wal_bytes(self) -> int:
        st = self._client.replica_status()
        return sum((st or {}).get("shards", {}).values())

    @property
    def replay_seconds(self) -> float:
        return 0.0

    def _sync_epoch(self, epoch: Optional[int]):
        if epoch is not None and epoch > (self._client.epoch or 0):
            self._client.epoch = int(epoch)
            self.epoch = max(self.epoch, int(epoch))

    def _adopt_after_election(self, e: StaleEpochError) -> bool:
        """True when the fence came from a KV election under the SAME
        driver (adopt + continue); False for a rival driver.

        The ownership record is read THROUGH THE LEADER (the leader-only
        ``/_replica/read`` endpoint), never a follower's local store: a
        genuinely fenced-out stale driver could otherwise hit a lagging
        follower, see its own old owner stamp, adopt the rival's epoch,
        and retry its mutation into a store the rival now owns —
        re-opening the split-brain this check exists to close. No leader
        reachable = ownership unprovable = stand down (the safe side)."""
        try:
            rec = self._client.get_json_leader(
                kv_keys.control_epoch(), attempts=20, backoff=0.2,
                deadline=15.0)
        except (urlerror.URLError, ConnectionError, OSError):
            _logger().warning(
                "driver KV handle: fenced at epoch %d and no KV leader "
                "reachable to verify ownership — standing down",
                e.offered)
            return False
        if not isinstance(rec, dict) or \
                rec.get("owner") != self._incarnation:
            return False
        new_epoch = max(int(e.current), int(rec.get("epoch", 0)))
        self.epoch = new_epoch
        self._client.epoch = new_epoch
        _logger().warning(
            "driver KV handle: adopting post-election control epoch %d "
            "(was fenced at %d; same driver incarnation)", new_epoch,
            e.offered)
        if self._on_epoch_adopted is not None:
            try:
                self._on_epoch_adopted(new_epoch)
            except Exception:  # noqa: BLE001
                pass
        return True

    def _mutate(self, fn):
        try:
            return fn()
        except StaleEpochError as e:
            if not self._adopt_after_election(e):
                raise
            return fn()  # once, at the adopted epoch

    def put_json(self, key: str, value: Any, epoch: Optional[int] = None):
        self._sync_epoch(epoch)
        # Ownership is handle-level bookkeeping: a driver re-publishing
        # the control epoch (recovery, topology notify) writes a plain
        # {"epoch"} payload and would otherwise clobber the owner stamp
        # `_adopt_after_election` depends on — after the next election
        # the handle would mistake its own driver for a rival and stand
        # down instead of adopting.
        stamp_owner = (key == kv_keys.control_epoch()
                       and isinstance(value, dict))
        # A payload whose embedded "epoch" equals the claimed epoch is a
        # driver command embedding its fencing token for workers. It is
        # rebuilt per attempt so a post-adoption retry carries the
        # adopted epoch — workers whose floor already rose past the
        # election would silently ignore the pre-fence value.
        refresh = (isinstance(value, dict) and epoch is not None
                   and value.get("epoch") == epoch)
        if not (stamp_owner or refresh):
            return self._mutate(lambda: self._client.put_json(
                key, value, attempts=6, backoff=0.1, deadline=30.0))

        def write():
            v = dict(value)
            if stamp_owner:
                v.setdefault("owner", self._incarnation)
            if isinstance(v.get("epoch"), int):
                v["epoch"] = max(v["epoch"], self.epoch)
            return self._client.put_json(
                key, v, attempts=6, backoff=0.1, deadline=30.0)
        return self._mutate(write)

    def get_json(self, key: str) -> Optional[Any]:
        # the in-process server returns immediately; so does the handle
        # (timeout covers transport + one failover rotation, not polling)
        return self._client.get_json(key, timeout=5.0, poll_interval=0.05)

    def delete(self, key: str, epoch: Optional[int] = None) -> bool:
        self._sync_epoch(epoch)
        self._mutate(lambda: self._client.delete(key, attempts=6))
        return True

    def delete_prefix(self, prefix: str, epoch: Optional[int] = None):
        self._sync_epoch(epoch)
        return self._mutate(
            lambda: self._client.delete_prefix(prefix, attempts=6))

    def keys(self, prefix: str = "") -> List[str]:
        try:
            return self._client.keys(prefix, attempts=6)
        except (urlerror.URLError, ConnectionError, OSError):
            return []

    def replica_status(self) -> Optional[dict]:
        return self._client.replica_status()


# ===========================================================================
# subprocess entry point
# ===========================================================================

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.runner.replica_kv",
        description="run one leader-lease KV replica")
    ap.add_argument("--id", type=int, required=True)
    ap.add_argument("--endpoints", required=True,
                    help="comma-separated host:port list, one per replica")
    ap.add_argument("--dir", required=True, help="this replica's kv_dir")
    ap.add_argument("--lease", type=float, default=None,
                    help="lease seconds (default HOROVOD_KV_LEASE_SECONDS)")
    args = ap.parse_args(argv)
    endpoints = [e.strip() for e in args.endpoints.split(",") if e.strip()]
    srv = ReplicaKVServer(args.id, endpoints, kv_dir=args.dir,
                          lease_seconds=args.lease).start()
    stop = threading.Event()

    def _sig(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    _logger().info("kv-replica %d serving on %s (of %s)", args.id,
                   endpoints[args.id], endpoints)
    stop.wait()
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
