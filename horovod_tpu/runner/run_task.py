"""Worker entry for the programmatic run() API.

Reference analog: horovod/runner/run_task.py + the SafeShell func wrapper
(runner/__init__.py:206 run(func) → per-worker func execution with the
return value shipped back to the launcher).

Executes the cloudpickled function and ships its return value back two
ways: a ``result.<rank>.pkl`` file in the results directory (covers
localhost and shared filesystems) and, when the launcher's rendezvous KV
is in the env, an HTTP PUT of the pickled value (covers remote hosts with
no shared filesystem — the role of the reference's task service,
runner/common/service/task_service.py). Start markers ride both channels
for the launcher's start_timeout.
"""

from __future__ import annotations

import base64
import os
import sys

import cloudpickle

from horovod_tpu.common import kv_keys
from horovod_tpu.common.env_registry import env_int, env_str


def _kv_client():
    addr = env_str("HOROVOD_RENDEZVOUS_ADDR")
    port = env_int("HOROVOD_RENDEZVOUS_PORT")
    if not addr or not port:
        return None
    from horovod_tpu.runner.http_kv import (KVClient,
                                            replica_endpoints_from_env)
    return KVClient(addr, port, endpoints=replica_endpoints_from_env())


def main():
    fn_path, out_dir = sys.argv[1], sys.argv[2]
    rank = env_int("HOROVOD_RANK")
    kv = _kv_client()
    try:
        with open(os.path.join(out_dir, f"started.{rank}"), "w"):
            pass
    except OSError:
        pass  # results dir not mounted here; the KV marker covers us
    if kv is not None:
        kv.put_json(kv_keys.task_started(rank), {"ok": True})
    if os.path.exists(fn_path):
        with open(fn_path, "rb") as f:
            fn = cloudpickle.load(f)
    elif kv is not None:
        # no shared filesystem: the launcher publishes the pickled
        # function under task_fn
        blob = kv.get_json(kv_keys.task_fn(), timeout=30.0)
        if blob is None:
            raise RuntimeError(f"{fn_path} absent and no task_fn in the "
                               "rendezvous KV")
        fn = cloudpickle.loads(base64.b64decode(blob["data"]))
    else:
        raise RuntimeError(f"function payload {fn_path} not found")
    result = fn()
    payload = cloudpickle.dumps(result)
    try:
        tmp = os.path.join(out_dir, f".result.{rank}.tmp")
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, os.path.join(out_dir, f"result.{rank}.pkl"))
    except OSError:
        if kv is None:
            raise
    if kv is not None:
        # generation-scoped: under elastic resets a rank's number is
        # recycled across world sizes — only the final generation's
        # results may be collected together. The env var tracks re-inits
        # (elastic/worker.py rewrites it at each rendezvous); static jobs
        # stay at generation 0.
        gen = env_int("HOROVOD_ELASTIC_GENERATION")
        kv.put_json(kv_keys.task_result(gen, rank),
                    {"data": base64.b64encode(payload).decode()})


if __name__ == "__main__":
    main()
