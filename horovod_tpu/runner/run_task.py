"""Worker entry for the programmatic run() API.

Reference analog: horovod/runner/run_task.py + the SafeShell func wrapper
(runner/__init__.py:206 run(func) → per-worker func execution with the
return value shipped back to the launcher).

Executes the cloudpickled function and drops its return value into the
shared results directory as ``result.<rank>.pkl``.
"""

from __future__ import annotations

import os
import sys

import cloudpickle


def main():
    fn_path, out_dir = sys.argv[1], sys.argv[2]
    rank0 = os.environ.get("HOROVOD_RANK", "0")
    # start marker: the launcher's start_timeout watches for these
    with open(os.path.join(out_dir, f"started.{rank0}"), "w"):
        pass
    with open(fn_path, "rb") as f:
        fn = cloudpickle.load(f)
    result = fn()
    rank = int(os.environ.get("HOROVOD_RANK", "0"))
    tmp = os.path.join(out_dir, f".result.{rank}.tmp")
    with open(tmp, "wb") as f:
        cloudpickle.dump(result, f)
    os.replace(tmp, os.path.join(out_dir, f"result.{rank}.pkl"))


if __name__ == "__main__":
    main()
