"""Worker-side elastic rendezvous: generation sync + READY/go barrier.

Reference analog: horovod/runner/elastic/worker.py (WorkerNotificationClient
side) + horovod/common/gloo/gloo_context.cc:154-200 (the re-init scope query
on reset). Here both the freshly-spawned and the resetting worker go through
the same handshake against the driver's rendezvous KV:

1. read the driver's current ``generation`` key,
2. fetch this slot's topology ``rank_and_size/g<GEN>/<host>/<local_rank>``
   (exit cleanly if the slot was removed),
3. record READY in the worker-state registry
   (``worker_state/g<GEN>/<host>/<slot>``, reference:
   runner/elastic/registration.py:66-135),
4. wait for the driver's ``go/g<GEN>`` key — published once every expected
   slot of the generation is READY — re-looping from (1) if the generation
   advances while waiting.

This barrier is what makes elastic resets deterministic: no worker can
initialize a generation that the driver is about to supersede, and the new
coordinator is only contacted once every peer has committed to the same
generation.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional, Tuple

from horovod_tpu.common import kv_keys
from horovod_tpu.common.env_registry import (env_bool, env_float, env_int,
                                             env_is_set, env_str)
from horovod_tpu.common.hvd_logging import get_logger
from horovod_tpu.runner.elastic.registration import (  # noqa: F401
    DRAINED,
    FAILURE,
    READY,
    SUCCESS,
    state_key,
)

_logger = get_logger("elastic.worker")


def kv_client():
    # with a replicated control plane the worker fails over across the
    # whole replica set (follows 307 leader redirects, rotates on
    # NotLeader/refused) instead of pinning the one rendezvous endpoint
    from horovod_tpu.runner.http_kv import (KVClient,
                                            replica_endpoints_from_env)
    return KVClient(env_str("HOROVOD_RENDEZVOUS_ADDR"),
                    env_int("HOROVOD_RENDEZVOUS_PORT"),
                    endpoints=replica_endpoints_from_env())


def is_elastic_worker() -> bool:
    """True when this process was spawned by the elastic driver."""
    return (env_bool("HOROVOD_ELASTIC")
            and env_is_set("HOROVOD_RENDEZVOUS_ADDR"))


def current_generation() -> int:
    """The topology generation this worker last rendezvoused into."""
    return env_int("HOROVOD_ELASTIC_GENERATION")


def _slot() -> Tuple[str, str]:
    return (env_str("HOROVOD_HOSTNAME"),
            str(env_int("HOROVOD_LOCAL_RANK")))


def heartbeat_key(host: str, slot) -> str:
    """KV key a worker's liveness heartbeat lands under — a recovered
    driver adopts live workers from these instead of respawning them."""
    return kv_keys.worker_heartbeat(host, slot)


# -- control-epoch fencing (worker side) ------------------------------------
# The highest control epoch this worker has observed. Spawn env seeds the
# floor; any driver command (notify / go / topology) carrying a strictly
# OLDER epoch is a lingering pre-crash driver and is rejected.

_epoch_floor: Optional[int] = None
_epoch_lock = threading.Lock()


def observe_epoch(epoch) -> bool:
    """True when ``epoch`` is current (None = unfenced legacy record, or
    at/above the floor — which it then raises); False for a strictly
    older claim, with a structured log naming both epochs."""
    global _epoch_floor
    if epoch is None:
        return True
    e = int(epoch)
    with _epoch_lock:
        if _epoch_floor is None:
            _epoch_floor = env_int("HOROVOD_CONTROL_EPOCH")
        if e < _epoch_floor:
            current = _epoch_floor
        else:
            _epoch_floor = e
            return True
    _logger.warning(
        "rejected stale driver command: %s",
        json.dumps({"event": "stale_epoch_rejected",
                    "offered": e, "current": current}))
    return False


def _reset_epoch_for_tests():
    global _epoch_floor
    with _epoch_lock:
        _epoch_floor = None


# -- KV liveness heartbeat + headless-mode probe ----------------------------

_heartbeat_started = False


def start_heartbeat(interval: Optional[float] = None):
    """Start the worker's KV heartbeat thread (idempotent; elastic
    workers only). Each beat PUTs ``worker_heartbeat/<host>/<slot>``
    (pid, rank, generation, wall ts) with a hard total deadline, and
    drives the headless-mode state machine: a failed beat starts/extends
    the outage clock (see :mod:`~horovod_tpu.runner.elastic.headless`),
    a successful one replays any deferred drain/handoff writes."""
    global _heartbeat_started
    if _heartbeat_started or not is_elastic_worker():
        return
    _heartbeat_started = True
    period = interval if interval is not None \
        else env_float("HOROVOD_WORKER_HEARTBEAT_SECONDS")
    host, slot = _slot()

    def loop():
        from horovod_tpu.runner.elastic import headless
        client = kv_client()
        while True:
            try:
                client.put_json(
                    heartbeat_key(host, slot),
                    {"pid": os.getpid(),
                     "rank": env_int("HOROVOD_RANK"),
                     "generation": current_generation(),
                     "ts": time.time()},
                    timeout=2.0, attempts=1,
                    deadline=max(0.5, period))
                headless.note_success(client)
            except Exception:  # noqa: BLE001 — outage, not a crash
                headless.note_failure()
            time.sleep(period)

    threading.Thread(target=loop, daemon=True,
                     name="hvd-kv-heartbeat").start()


def _reset_heartbeat_for_tests():
    global _heartbeat_started
    _heartbeat_started = False


def record_state(generation: int, state: str, client=None,
                 attempts: int = 3, deadline: Optional[float] = None):
    """Record READY/SUCCESS/FAILURE for this slot (registry PUT side).

    ``attempts``/``deadline`` let the *final* record (SUCCESS/FAILURE at
    exit) ride out a driver-restart window: an exit code is truth for a
    driver that spawned the process, but a *recovered* driver only has
    the registry — a success record lost to a mid-restart KV reads as a
    worker failure and triggers a spurious resize."""
    host, local_rank = _slot()
    (client or kv_client()).put_json(
        state_key(generation, host, local_rank),
        {"state": state, "ts": time.time()},
        attempts=attempts, deadline=deadline)


def request_new_generation():
    """Mark that the next rendezvous must land on a strictly newer
    generation than the one this worker is leaving.

    Called on elastic reset after a HorovodInternalError: the generation
    this worker crashed out of may still be the driver's current one (its
    ``go`` already published), and rejoining it would re-init against a
    topology that includes the dead peer. The pending minimum makes
    ``rendezvous()`` ask the driver for a fresh round instead (reference:
    WorkerStateRegistry READY records triggering a new rendezvous,
    runner/elastic/registration.py:66-135)."""
    os.environ["HOROVOD_ELASTIC_MIN_GENERATION"] = \
        str(current_generation() + 1)


def rendezvous(timeout: float = 300.0) -> int:
    """Synchronize this slot with the driver's current generation.

    Applies the fetched topology to the ``HOROVOD_*`` env (so a subsequent
    ``init()`` picks it up) and returns the generation joined. Raises
    SystemExit(0) if this slot was removed from the job, RuntimeError if the
    rendezvous server is unreachable or the barrier times out.
    """
    client = kv_client()
    host, local_rank = _slot()
    min_gen = env_int("HOROVOD_ELASTIC_MIN_GENERATION")
    deadline = time.monotonic() + timeout
    while True:
        gen_info = client.get_json(kv_keys.generation(), timeout=60.0)
        if gen_info is None:
            raise RuntimeError(
                "rendezvous server unreachable during elastic rendezvous")
        gen = gen_info["generation"]
        if gen < min_gen:
            # ask the driver for a fresh round (it rebalances on seeing the
            # request) and wait for the generation to advance
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"driver never advanced past generation {gen} "
                    f"(need >= {min_gen})")
            client.put_json(kv_keys.reset_request(gen),
                            {"slot": f"{host}/{local_rank}",
                             "ts": time.time()})
            time.sleep(0.3)
            continue
        info = client.get_json(kv_keys.rank_and_size(gen, host, local_rank),
                               timeout=30.0)
        if info is not None and not observe_epoch(info.get("epoch")):
            # topology published by a fenced-out pre-crash driver: wait
            # for the current driver's record instead of re-initializing
            # into a stale resize
            info = None
        if info is None:
            # Generation published without this slot: either we were dropped
            # (the driver marks removed slots explicitly) or the driver is
            # mid-publish; re-read the generation and retry briefly.
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no topology for slot {host}/{local_rank} at "
                    f"generation {gen}")
            time.sleep(0.2)
            continue
        if info.get("removed"):
            raise SystemExit(0)  # host removed from the job: exit cleanly
        record_state(gen, READY, client)
        joined = _wait_go(client, gen, deadline)
        if joined:
            _apply_env(gen, info)
            os.environ.pop("HOROVOD_ELASTIC_MIN_GENERATION", None)
            return gen
        # generation advanced while waiting — re-rendezvous


def _wait_go(client, gen: int, deadline: float) -> bool:
    """Wait for go/g<gen>; False if the generation advances first."""
    while True:
        go = client.get_json(kv_keys.go(gen), timeout=1.0)
        if go is not None and observe_epoch(
                go.get("epoch") if isinstance(go, dict) else None):
            return True
        cur = client.get_json(kv_keys.generation(), timeout=1.0)
        if cur is not None and cur["generation"] > gen:
            return False
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"elastic go-barrier timed out at generation {gen}")


def _apply_env(gen: int, info: dict):
    for k in ("rank", "size", "local_rank", "local_size", "cross_rank",
              "cross_size"):
        if k in info:
            os.environ[f"HOROVOD_{k.upper()}"] = str(info[k])
    os.environ["HOROVOD_CONTROLLER_ADDR"] = info["controller_addr"]
    os.environ["HOROVOD_CONTROLLER_PORT"] = str(info["controller_port"])
    os.environ["HOROVOD_CONTROLLER_DATA_PORT"] = \
        str(info["controller_data_port"])
    os.environ["HOROVOD_ELASTIC_GENERATION"] = str(gen)


def poll_notification(client=None) -> Optional[int]:
    """Return the driver's announced generation if it is newer than the one
    this worker rendezvoused into (reference: WorkerNotificationService push,
    here a poll of the ``notify`` key)."""
    try:
        info = (client or kv_client()).get_json(kv_keys.notify(),
                                                 timeout=5.0)
    except Exception:  # noqa: BLE001 — rendezvous may be restarting
        return None
    if info and not observe_epoch(info.get("epoch")):
        return None  # a fenced-out stale driver cannot trigger resets
    if info and info["generation"] > current_generation():
        return info["generation"]
    return None
