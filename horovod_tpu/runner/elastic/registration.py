"""Worker state registry.

Reference analog: horovod/runner/elastic/registration.py — the
READY/SUCCESS/FAILURE barrier (:66-135) driving re-rendezvous: the driver
waits until every expected worker of a generation has recorded READY before
publishing the go-ahead, and uses SUCCESS/FAILURE records to decide
completion vs reset.

This build records states in the rendezvous KV
(``worker_state/g<GEN>/<host>/<slot>``) — workers PUT, the driver polls.
"""

from __future__ import annotations

import time
from typing import Dict, Tuple

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"
# Preemption-notice departure: the slot left on purpose (drain protocol,
# runner/elastic/preempt.py). Counts as neither SUCCESS (the job is not
# done) nor FAILURE (the host is not at fault — no blacklist strike).
DRAINED = "DRAINED"


def state_key(generation: int, hostname, local_rank) -> str:
    """KV key for a slot's state record — the single definition shared by
    the worker (PUT side) and the driver's registry (poll side)."""
    from horovod_tpu.common import kv_keys
    return kv_keys.worker_state(generation, hostname, local_rank)


class WorkerStateRegistry:
    def __init__(self, kv_server):
        self._kv = kv_server

    def key(self, generation: int, hostname: str, local_rank: int) -> str:
        return state_key(generation, hostname, local_rank)

    def record(self, generation: int, hostname: str, local_rank: int,
               state: str):
        self._kv.put_json(self.key(generation, hostname, local_rank),
                          {"state": state, "ts": time.time()})

    def get(self, generation: int, hostname: str,
            local_rank: int) -> str:
        v = self._kv.get_json(self.key(generation, hostname, local_rank))
        return v["state"] if v else None

    def count(self, generation: int,
              slots: Dict[Tuple[str, int], None]) -> Dict[str, int]:
        counts = {READY: 0, SUCCESS: 0, FAILURE: 0, DRAINED: 0, None: 0}
        for (host, local_rank) in slots:
            state = self.get(generation, host, local_rank)
            counts[state] = counts.get(state, 0) + 1
        return counts
