"""Bounded worker autonomy under control-plane loss ("headless" mode).

When the elastic driver (and the rendezvous KV it hosts) disappears, the
*data plane* between workers is untouched — training collectives keep
flowing peer-to-peer. Killing a healthy 64-rank job because its metadata
service restarted would be self-inflicted damage, so workers degrade
instead of dying:

    CONNECTED --KV write fails--> HEADLESS --KV write succeeds--> CONNECTED
                                     |
         sustained outage > HOROVOD_HEADLESS_DEADLINE_SECONDS --> abort

While HEADLESS:

- training continues (nothing here blocks the step path);
- control-plane writes that must not be lost (drain announcements, shard
  handoffs) are **queued** via :func:`queue_write` and replayed in order
  on reconnect;
- ``hvd_driver_unreachable_seconds`` tracks the outage for scrapes and
  the BENCH ``control_plane`` block;
- only an outage longer than the deadline aborts (the driver is then
  presumed permanently gone and an unsupervised job would leak forever).

The worker KV heartbeat thread (:func:`runner.elastic.worker
.start_heartbeat`) is the probe that drives the transitions.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, List, Optional, Tuple

from horovod_tpu.common import journal
from horovod_tpu.common.env_registry import env_float
from horovod_tpu.common.hvd_logging import get_logger

UNREACHABLE_SECONDS = "hvd_driver_unreachable_seconds"

# queued control-plane writes are small JSON blobs; past this the oldest
# are dropped loudly (an unbounded queue during an hours-long outage is a
# memory leak wearing a durability costume)
_QUEUE_LIMIT = 1024

_logger = get_logger("elastic.headless")
_lock = threading.Lock()
_outage_start: Optional[float] = None
_queue: List[Tuple[str, dict]] = []
_abort_hook: Optional[Callable[[float], None]] = None


def _default_abort(outage_seconds: float):
    _logger.error(
        "headless deadline exceeded: %s",
        json.dumps({"event": "headless_deadline_exceeded",
                    "outage_seconds": round(outage_seconds, 1)}))
    journal.emit("worker", "headless_abort",
                 outage_seconds=round(outage_seconds, 1))
    os._exit(75)  # EX_TEMPFAIL: the control plane never came back


def set_abort_hook(hook: Optional[Callable[[float], None]]):
    """Override the deadline action (tests; schedulers that prefer a
    checkpoint-and-exit over a hard abort)."""
    global _abort_hook
    with _lock:
        _abort_hook = hook


def _gauge():
    from horovod_tpu.metrics.registry import get_registry
    return get_registry().gauge(
        UNREACHABLE_SECONDS,
        "seconds the driver/KV has been unreachable (0 = connected)")


def is_headless() -> bool:
    with _lock:
        return _outage_start is not None


def unreachable_seconds() -> float:
    with _lock:
        if _outage_start is None:
            return 0.0
        return time.monotonic() - _outage_start


def queue_write(key: str, value: dict):
    """Defer a control-plane write until the driver returns. Order is
    preserved; overflow drops the oldest entry loudly."""
    with _lock:
        _queue.append((key, value))
        dropped = len(_queue) - _QUEUE_LIMIT
        if dropped > 0:
            del _queue[:dropped]
    if dropped > 0:
        _logger.warning("headless write queue overflow: dropped %d "
                        "oldest deferred write(s)", dropped)


def pending_writes() -> int:
    with _lock:
        return len(_queue)


def note_failure():
    """One failed KV probe: enter (or extend) the outage. Called by the
    heartbeat thread; transitions and the deadline check live here so the
    probe site stays one line."""
    global _outage_start
    with _lock:
        if _outage_start is None:
            _outage_start = time.monotonic()
            entered = True
        else:
            entered = False
        outage = time.monotonic() - _outage_start
        hook = _abort_hook
    try:
        _gauge().set(outage)
    except Exception:  # noqa: BLE001 — metrics must not break the probe
        pass
    if entered:
        _logger.warning(
            "driver unreachable: %s",
            json.dumps({"event": "headless_entered"}))
        journal.emit("worker", "headless_entered")
    deadline = env_float("HOROVOD_HEADLESS_DEADLINE_SECONDS")
    if deadline and deadline > 0 and outage > deadline:
        (hook or _default_abort)(outage)


def note_success(client=None):
    """One successful KV probe: leave headless mode and replay the
    deferred writes in order. ``client`` is the KVClient to replay
    through (omit to skip replay — e.g. probes that cannot write)."""
    global _outage_start
    with _lock:
        was = _outage_start
        _outage_start = None
        pending = list(_queue) if client is not None else []
        if client is not None:
            _queue.clear()
    try:
        _gauge().set(0.0)
    except Exception:  # noqa: BLE001
        pass
    if was is not None:
        journal.emit("worker", "headless_exited",
                     outage_seconds=round(time.monotonic() - was, 1),
                     replaying_writes=len(pending))
        _logger.warning(
            "driver reachable again: %s",
            json.dumps({"event": "headless_exited",
                        "outage_seconds":
                            round(time.monotonic() - was, 1),
                        "replaying_writes": len(pending)}))
    for i, (key, value) in enumerate(pending):
        try:
            client.put_json(key, value, attempts=1, deadline=2.0)
        except Exception as e:  # noqa: BLE001 — KV flapped again: requeue
            _logger.warning("deferred write replay failed (%r); "
                            "requeueing", e)
            with _lock:
                _queue[:0] = pending[i:]  # current + unreplayed tail
            note_failure()
            return


def _reset_for_tests():
    global _outage_start, _abort_hook
    with _lock:
        _outage_start = None
        _queue.clear()
        _abort_hook = None
