"""Traffic-driven autoscaler: serving SLOs in, fleet size out.

The policy loop that closes the gap ROADMAP calls the most direct "heavy
traffic" demonstration the repo can make: the ``hvd_serve_*`` families
(PR 8) already say when a fleet is drowning or idle, checkpoint-free
resize + drain (PR 9) already grows and shrinks a fleet without dropping
work, and the epoch-fenced driver (PR 10) already survives crashes — this
module connects them.

Two layers, deliberately separable:

- :class:`AutoscalePolicy` — pure decision logic. Each observation window
  it classifies the fleet as *breached* (any worker's queue depth over
  ``HOROVOD_AUTOSCALE_QUEUE_BOUND`` or p99 over
  ``HOROVOD_AUTOSCALE_P99_MS_BOUND``), *idle* (every queue empty and mean
  in-flight per worker at or under ``HOROVOD_AUTOSCALE_IDLE_OCCUPANCY``),
  or neither.
  A decision needs a **sustained streak** (``HOROVOD_AUTOSCALE_UP_WINDOWS``
  / ``DOWN_WINDOWS`` consecutive windows — hysteresis: a one-window spike
  never resizes), respects **per-direction cooldowns** (``UP_COOLDOWN`` /
  ``DOWN_COOLDOWN`` — shedding capacity is the riskier direction, so its
  default is longer), and clamps to ``[MIN_WORKERS, MAX_WORKERS]``.
  Scale-down picks the **least-loaded non-draining** worker and drains it
  through the PR-9 preemption machinery — never a kill.

- :class:`Autoscaler` — the KV-recording state machine around the policy.
  Every decision is an **epoch-claimed** record under
  ``autoscale/decision`` advancing ``decide → drain → resize → ack``
  (scale-up skips ``drain``), written *before* the action it describes.
  A recovered driver calls :meth:`Autoscaler.recover` and **resumes** a
  half-finished decision instead of re-deciding — the crash-window story
  :class:`~horovod_tpu.verify.specs.AutoscaleSpec` model-checks, mutants
  included. Acked decisions append an ``autoscale/event/<seq>`` audit
  record.

The driver side (``runner/elastic/driver.py``) feeds the loop from the
same ``/metrics.json`` scrape that powers straggler detection, and acts
on it by moving its live target fleet size and SIGTERMing scale-down
victims (the preemption-notice drain path). The in-process fleet sim
(``serve/autoscale_smoke.py``) drives the identical Autoscaler against a
router+batcher fleet for the BENCH ``autoscale`` block.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

from horovod_tpu.common import journal, kv_keys
from horovod_tpu.common.env_registry import env_float, env_int
from horovod_tpu.common.hvd_logging import get_logger
from horovod_tpu.metrics.registry import MetricsRegistry, get_registry

UP = "up"
DOWN = "down"
HOLD = "hold"

# Decision-record states (the decide→drain→resize→ack machine).
DECIDE = "decide"
DRAIN = "drain"
RESIZE = "resize"
ACK = "ack"


class WorkerSLO(NamedTuple):
    """One worker's serving-health sample for a policy window."""
    key: str                      # "host/local_rank"
    queue_depth: float
    p99_ms: Optional[float]
    occupancy: Optional[float]    # mean batch occupancy (0..max_batch)
    inflight: float


def worker_slo_from_snapshot(key: str, snap: dict,
                             max_batch: Optional[float] = None) \
        -> Optional[WorkerSLO]:
    """Extract a :class:`WorkerSLO` from a ``/metrics.json`` snapshot, or
    None when the worker exports no serving metrics (a pure training
    rank must not read as an idle serving worker)."""
    from horovod_tpu.metrics import (histogram_quantile, snapshot_histogram,
                                     snapshot_value)
    qd = snapshot_value(snap, "hvd_serve_queue_depth")
    if qd is None:
        return None
    lat = snapshot_histogram(snap, "hvd_serve_request_latency_seconds")
    p99 = histogram_quantile(lat, 0.99) if lat else None
    occ = snapshot_histogram(snap, "hvd_serve_batch_occupancy")
    occupancy = occ["sum"] / occ["count"] if occ and occ["count"] else None
    if occupancy is not None and max_batch:
        occupancy = occupancy / max_batch
    return WorkerSLO(
        key=key, queue_depth=float(qd),
        p99_ms=p99 * 1e3 if p99 is not None else None,
        occupancy=occupancy,
        inflight=float(snapshot_value(snap, "hvd_serve_inflight") or 0.0))


def slo_headroom(queue_depth: Optional[float], p99_ms: Optional[float],
                 queue_bound: Optional[float] = None,
                 p99_bound_ms: Optional[float] = None) -> Optional[float]:
    """Fractional distance to the nearest SLO bound, in [-1, 1]: 1.0 =
    fully idle, 0.0 = at the bound, negative = breached. The shared
    formula behind the policy's breach test and ``hvd-top --autoscale``'s
    HEADRM column."""
    if queue_bound is None:
        queue_bound = env_int("HOROVOD_AUTOSCALE_QUEUE_BOUND")
    if p99_bound_ms is None:
        p99_bound_ms = env_float("HOROVOD_AUTOSCALE_P99_MS_BOUND")
    rooms = []
    if queue_depth is not None and queue_bound > 0:
        rooms.append((queue_bound - queue_depth) / queue_bound)
    if p99_ms is not None and p99_bound_ms > 0:
        rooms.append((p99_bound_ms - p99_ms) / p99_bound_ms)
    if not rooms:
        return None
    return max(-1.0, min(1.0, min(rooms)))


class Decision(NamedTuple):
    action: str                 # UP | DOWN | HOLD
    victim: Optional[str]       # DOWN only: "host/local_rank"
    reason: str


class AutoscalePolicy:
    """Hysteresis + cooldown + clamp logic; no I/O, fully test-drivable.

    Call :meth:`update` once per observation window, then :meth:`decide`
    when no prior decision is in flight (the :class:`Autoscaler` does
    both in its tick)."""

    def __init__(self, min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 queue_bound: Optional[float] = None,
                 p99_bound_ms: Optional[float] = None,
                 idle_occupancy: Optional[float] = None,
                 up_windows: Optional[int] = None,
                 down_windows: Optional[int] = None,
                 up_cooldown: Optional[float] = None,
                 down_cooldown: Optional[float] = None):
        self.min_workers = min_workers if min_workers is not None \
            else env_int("HOROVOD_AUTOSCALE_MIN_WORKERS")
        self.max_workers = max_workers if max_workers is not None \
            else env_int("HOROVOD_AUTOSCALE_MAX_WORKERS")
        self.queue_bound = queue_bound if queue_bound is not None \
            else float(env_int("HOROVOD_AUTOSCALE_QUEUE_BOUND"))
        self.p99_bound_ms = p99_bound_ms if p99_bound_ms is not None \
            else env_float("HOROVOD_AUTOSCALE_P99_MS_BOUND")
        self.idle_occupancy = idle_occupancy if idle_occupancy is not None \
            else env_float("HOROVOD_AUTOSCALE_IDLE_OCCUPANCY")
        self.up_windows = up_windows if up_windows is not None \
            else env_int("HOROVOD_AUTOSCALE_UP_WINDOWS")
        self.down_windows = down_windows if down_windows is not None \
            else env_int("HOROVOD_AUTOSCALE_DOWN_WINDOWS")
        self.up_cooldown = up_cooldown if up_cooldown is not None \
            else env_float("HOROVOD_AUTOSCALE_UP_COOLDOWN_SECONDS")
        self.down_cooldown = down_cooldown if down_cooldown is not None \
            else env_float("HOROVOD_AUTOSCALE_DOWN_COOLDOWN_SECONDS")
        self.hot_streak = 0
        self.idle_streak = 0
        self._last_up: Optional[float] = None
        self._last_down: Optional[float] = None

    # -- observation ---------------------------------------------------------

    def classify(self, fleet: Sequence[WorkerSLO]) -> str:
        """One window's verdict: "breach" | "idle" | "ok"."""
        if not fleet:
            return "ok"
        for w in fleet:
            room = slo_headroom(w.queue_depth, w.p99_ms,
                                self.queue_bound, self.p99_bound_ms)
            if room is not None and room < 0:
                return "breach"
        if all(w.queue_depth == 0 for w in fleet):
            mean_inflight = sum(w.inflight for w in fleet) / len(fleet)
            if mean_inflight <= self.idle_occupancy:
                return "idle"
        return "ok"

    def update(self, fleet: Sequence[WorkerSLO]) -> str:
        """Advance the hysteresis streaks with one window; returns the
        window's classification."""
        verdict = self.classify(fleet)
        self.hot_streak = self.hot_streak + 1 if verdict == "breach" else 0
        self.idle_streak = self.idle_streak + 1 if verdict == "idle" else 0
        return verdict

    # -- decisions -----------------------------------------------------------

    def _cooled(self, last: Optional[float], cooldown: float,
                now: float) -> bool:
        return last is None or now - last >= cooldown

    def decide(self, fleet: Sequence[WorkerSLO],
               draining: Sequence[str] = (),
               now: Optional[float] = None) -> Decision:
        """The direction (if any) the streaks currently justify. Stamps
        the per-direction cooldown and resets both streaks on a non-HOLD
        result, so callers must act on what they get."""
        now = time.monotonic() if now is None else now
        size = len(fleet)
        if self.hot_streak >= self.up_windows:
            if size >= self.max_workers:
                return Decision(HOLD, None,
                                f"breached but at max_workers="
                                f"{self.max_workers}")
            if not self._cooled(self._last_up, self.up_cooldown, now):
                return Decision(HOLD, None, "scale-up cooling down")
            self._last_up = now
            self.hot_streak = self.idle_streak = 0
            return Decision(
                UP, None,
                f"SLO breached {self.up_windows}+ consecutive windows")
        if self.idle_streak >= self.down_windows:
            if size - 1 < self.min_workers:
                return Decision(HOLD, None,
                                f"idle but at min_workers="
                                f"{self.min_workers}")
            if not self._cooled(self._last_down, self.down_cooldown, now):
                return Decision(HOLD, None, "scale-down cooling down")
            victim = self.pick_victim(fleet, draining)
            if victim is None:
                return Decision(HOLD, None,
                                "idle but no non-draining victim")
            self._last_down = now
            self.hot_streak = self.idle_streak = 0
            return Decision(
                DOWN, victim,
                f"fleet idle {self.down_windows}+ consecutive windows")
        return Decision(HOLD, None, "")

    @staticmethod
    def pick_victim(fleet: Sequence[WorkerSLO],
                    draining: Sequence[str] = ()) -> Optional[str]:
        """Least-loaded *sheddable* worker NOT already draining (ties by
        key for determinism). Selecting a draining worker would
        double-resize and strand its acked requests — the seeded
        ``autoscale_victim_draining`` mutant proves the checker catches
        exactly that.

        Sheddable: the elastic assignment packs local_ranks contiguously
        per host (``hosts.get_host_assignments``), so on a multi-slot
        host only the HIGHEST occupied slot can actually leave the
        topology — draining a lower one would evict a different,
        healthy worker at the rebalance. Keys without a ``host/slot``
        shape (the fleet sim's flat ids) are all sheddable."""
        candidates = [w for w in fleet if w.key not in set(draining)]
        top_slot: Dict[str, tuple] = {}
        for w in candidates:
            host, sep, slot = w.key.rpartition("/")
            if not sep or not slot.isdigit():
                top_slot[w.key] = (0, w)
                continue
            s = int(slot)
            cur = top_slot.get(host)
            if cur is None or s > cur[0]:
                top_slot[host] = (s, w)
        sheddable = [w for _s, w in top_slot.values()]
        if not sheddable:
            return None
        return min(sheddable,
                   key=lambda w: (w.inflight, w.queue_depth, w.key)).key


class Autoscaler:
    """The policy wrapped in the epoch-claimed KV decision machine.

    ``fleet_ops`` is the actuation surface (duck-typed; the elastic
    driver and the fleet sim both provide one):

    - ``scale_up()`` — begin adding one worker (asynchronous; completion
      is observed as fleet growth on later ticks);
    - ``start_drain(victim_key)`` — begin draining a worker through the
      preemption machinery (never a kill; completion is observed as the
      victim leaving the fleet and then the draining set).

    ``kv`` is any ``put_json(key, value, epoch=...)`` /
    ``get_json(key)`` surface (KVServer, KVClient) or None for a
    KV-less policy loop (the fleet sim's default)."""

    def __init__(self, fleet_ops, kv=None, epoch: int = 0,
                 policy: Optional[AutoscalePolicy] = None,
                 registry: Optional[MetricsRegistry] = None,
                 pending_timeout: float = 120.0):
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.fleet_ops = fleet_ops
        self.kv = kv
        self.epoch = epoch
        self.pending: Optional[dict] = None
        self.decisions: List[dict] = []       # acted decisions, in order
        self._seq = 0
        self._pending_since: Optional[float] = None
        self._pending_timeout = pending_timeout
        self._target: Optional[int] = None
        self._log = get_logger("elastic.autoscaler")
        reg = registry if registry is not None else get_registry()
        self._g_fleet = reg.gauge(
            "hvd_autoscale_fleet_size",
            "accepting serving workers at the last observation")
        self._g_last = reg.gauge(
            "hvd_autoscale_last_decision",
            "last decision direction (+1 up, -1 down, 0 none yet)")
        self._c_up = reg.counter("hvd_autoscale_up_total",
                                 "scale-up decisions acted on")
        self._c_down = reg.counter("hvd_autoscale_down_total",
                                   "scale-down (drain) decisions acted on")
        self._g_pending = reg.gauge(
            "hvd_autoscale_pending",
            "1 while a decision is between decide and ack")

    # -- KV record -----------------------------------------------------------

    def _write(self, state: str, **extra):
        assert self.pending is not None
        self.pending = dict(self.pending, state=state, ts=time.time(),
                            **extra)
        if self.kv is not None:
            self.kv.put_json(kv_keys.autoscale_decision(), self.pending,
                             epoch=self.epoch)
        journal.emit("autoscaler", f"autoscale_{state}",
                     control_epoch=self.epoch,
                     seq=self.pending.get("seq"),
                     action=self.pending.get("action"),
                     victim=self.pending.get("victim"),
                     outcome=extra.get("outcome"))

    def _open(self, decision: Decision, fleet_size: int):
        self._seq += 1
        self.pending = {
            "seq": self._seq, "action": decision.action,
            "victim": decision.victim, "reason": decision.reason,
            "fleet": fleet_size, "epoch": self.epoch, "state": DECIDE,
            "ts": time.time(),
        }
        self._pending_since = time.monotonic()
        if self.kv is not None:
            self.kv.put_json(kv_keys.autoscale_decision(), self.pending,
                             epoch=self.epoch)
        journal.emit("autoscaler", "autoscale_decide",
                     control_epoch=self.epoch, seq=self._seq,
                     action=decision.action, victim=decision.victim,
                     reason=decision.reason, fleet=fleet_size)
        self._g_pending.set(1)

    def _ack(self, outcome: str = "completed"):
        self._write(ACK, outcome=outcome)
        rec = self.pending
        self.decisions.append(rec)
        if self.kv is not None:
            self.kv.put_json(kv_keys.autoscale_event(rec["seq"]), rec,
                             epoch=self.epoch)
        self._log.warning("autoscale decision acked: %s", json.dumps(rec))
        self.pending = None
        self._pending_since = None
        self._target = None
        self._g_pending.set(0)

    def recover(self) -> Optional[dict]:
        """Adopt a predecessor driver's in-flight decision from the KV —
        the recovered driver *resumes* a half-finished resize instead of
        re-deciding (and instead of leaving a drained worker's slot
        half-removed). Returns the adopted record, or None."""
        if self.kv is None:
            return None
        rec = self.kv.get_json(kv_keys.autoscale_decision())
        if not isinstance(rec, dict):
            return None
        self._seq = max(self._seq, int(rec.get("seq", 0)))
        if rec.get("state") == ACK:
            return None
        self.pending = dict(rec, epoch=self.epoch, resumed=True)
        self._pending_since = time.monotonic()
        self._g_pending.set(1)
        self._log.warning(
            "autoscale recovery: resuming %s decision seq %s at state %s "
            "(old epoch %s -> %s)", rec.get("action"), rec.get("seq"),
            rec.get("state"), rec.get("epoch"), self.epoch)
        journal.emit("autoscaler", "autoscale_resume",
                     control_epoch=self.epoch, seq=rec.get("seq"),
                     action=rec.get("action"), state=rec.get("state"),
                     old_epoch=rec.get("epoch"))
        # the re-claimed record fences the dead driver's epoch out of the
        # rest of this decision's writes
        if self.kv is not None:
            self.kv.put_json(kv_keys.autoscale_decision(), self.pending,
                             epoch=self.epoch)
        return self.pending

    # -- the loop ------------------------------------------------------------

    def tick(self, fleet: Sequence[WorkerSLO],
             draining: Sequence[str] = (),
             now: Optional[float] = None):
        """One observation window: advance hysteresis, then either push
        the in-flight decision through its state machine or (when clear)
        ask the policy for a new one."""
        now = time.monotonic() if now is None else now
        self._g_fleet.set(len(fleet))
        self.policy.update(fleet)
        if self.pending is not None:
            self._advance(fleet, draining)
            return
        decision = self.policy.decide(fleet, draining, now=now)
        if decision.action == HOLD:
            return
        self._open(decision, len(fleet))
        self._log.warning("autoscale decision: %s",
                          json.dumps(self.pending))
        if decision.action == UP:
            self._g_last.set(1)
            self._c_up.inc()
            self._target = len(fleet) + 1
            self.fleet_ops.scale_up()
            # members at decide time: completion is "a NEW worker
            # joined", not an absolute size — a concurrent kill must not
            # wedge the decision open forever
            self._write(RESIZE, target=self._target,
                        members=sorted(w.key for w in fleet))
        else:
            self._g_last.set(-1)
            self._c_down.inc()
            self.fleet_ops.start_drain(decision.victim)
            self._write(DRAIN)

    def _advance(self, fleet: Sequence[WorkerSLO],
                 draining: Sequence[str]):
        rec = self.pending
        state = rec.get("state")
        if self._pending_since is not None and \
                time.monotonic() - self._pending_since > \
                self._pending_timeout:
            self._log.error(
                "autoscale decision seq %s stuck in state %s for %.0fs; "
                "abandoning (fleet may not match the decision)",
                rec.get("seq"), state, self._pending_timeout)
            self._ack(outcome="timeout")
            return
        if state == DECIDE:
            # a resumed record caught between decide and the first act:
            # re-issue the action idempotently
            if rec["action"] == UP:
                self._target = int(rec.get("target") or len(fleet) + 1)
                self.fleet_ops.scale_up()
                self._write(RESIZE, target=self._target,
                            members=sorted(w.key for w in fleet))
            else:
                victim = rec.get("victim")
                keys = {w.key for w in fleet}
                if victim in keys and victim not in set(draining):
                    self.fleet_ops.start_drain(victim)
                    self._write(DRAIN)
                else:
                    # victim already gone (the drain outlived the crash)
                    self._write(DRAIN)
            return
        if state == RESIZE and rec["action"] == UP:
            target = int(rec.get("target") or 0)
            members = set(rec.get("members") or ())
            joined = any(w.key not in members for w in fleet)
            if len(fleet) >= target or joined:
                self._ack()
            return
        victim = rec.get("victim")
        in_fleet = any(w.key == victim for w in fleet)
        if state == DRAIN:
            if not in_fleet:
                self._write(RESIZE)
            return
        if state == RESIZE:  # DOWN: wait for the drain to fully clear
            if not in_fleet and victim not in set(draining):
                self._ack()


def autoscale_status(kv_get_json: Callable[[str], Optional[dict]]) \
        -> Optional[dict]:
    """The current decision record + its age — what ``hvd-top
    --autoscale`` renders in its banner. ``kv_get_json`` is any
    ``key -> dict|None`` getter."""
    try:
        rec = kv_get_json(kv_keys.autoscale_decision())
    except Exception:  # noqa: BLE001 — KV outage: banner shows nothing
        return None
    if not isinstance(rec, dict):
        return None
    out = dict(rec)
    ts = rec.get("ts")
    out["age_seconds"] = round(time.time() - float(ts), 1) \
        if ts is not None else None
    return out
