"""Elastic driver: discovery polling, rank reassignment, worker lifecycle.

Reference analog: horovod/runner/elastic/driver.py — the background
discovery thread (:181-201), host-change notification + rank reassignment
(:202-274), worker spawn for new slots (:276-294) and failure handling with
blacklisting (:296+).

Topology generations: every membership change bumps a generation; the new
per-slot topology (plus fresh controller ports — the old coordinator may be
gone) is published to the rendezvous KV under ``rank_and_size/g<N>/...``.
Workers learn about the change either by a collective failure
(HorovodInternalError) or the notify key (polled inside the training
process, reference: WorkerNotificationService, runner/elastic/worker.py),
then reset: shutdown engine → re-query topology → re-init.

Cluster health (observability layer): workers running with
``HOROVOD_METRICS_PORT`` publish their metrics endpoint to the rendezvous
KV (``metrics_addr/<host>/<slot>``); the discovery heartbeat scrapes each
worker's ``/metrics.json``, diffs the shared step-time histogram into a
per-rank mean step time per window, and flags stragglers (> k sigma slower
than the peer median for M consecutive windows — HOROVOD_STRAGGLER_STDDEVS
/ HOROVOD_STRAGGLER_WINDOWS) as structured JSON events: logged, kept in
``straggler_events``, and published under ``straggler/g<N>/<rank>`` so
schedulers can act on them the way the stall-inspector report is actionable
inside the engine.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from horovod_tpu.common import journal, kv_keys
from horovod_tpu.common.env_registry import (env_bool, env_float, env_int,
                                             env_is_set, env_str)
from horovod_tpu.common.hvd_logging import get_logger
from horovod_tpu.metrics.aggregator import TieredScrape
from horovod_tpu.metrics.registry import get_registry
from horovod_tpu.metrics.straggler import StragglerDetector

from horovod_tpu.runner import hosts as hosts_lib
from horovod_tpu.runner.elastic.discovery import HostDiscovery, HostManager
from horovod_tpu.runner.elastic.registration import (
    FAILURE,
    READY,
    SUCCESS,
    WorkerStateRegistry,
)
from horovod_tpu.runner.exec_utils import AdoptedWorker, WorkerProcess
from horovod_tpu.runner.http_kv import KVServer
from horovod_tpu.runner.launch import (
    free_ports,
    launcher_addr,
    publish_assignments,
    worker_env,
)

DISCOVER_INTERVAL_SECS = 1.0
# Default for HOROVOD_FAILURES_TO_BLACKLIST: consecutive-ish worker
# failures on a host before it is blacklisted (until the blacklist
# cooldown re-admits it — see elastic/discovery.py). A clean generation
# (every slot READY) clears a host's failure count.
FAILURES_TO_BLACKLIST = 3
# Fallback: publish go/g<N> even without full READY after this long, so a
# worker that dies pre-READY cannot wedge the whole generation (its exit is
# separately detected and triggers the next rebalance).
GO_BARRIER_TIMEOUT_SECS = 60.0


class _DriverFleetOps:
    """The Autoscaler's actuation surface over a live ElasticDriver:
    scale-up moves the target fleet size, scale-down drains the victim
    through the preemption machinery (runner/elastic/preempt.py)."""

    def __init__(self, driver: "ElasticDriver"):
        self._driver = driver

    def scale_up(self):
        self._driver.request_scale_up()

    def start_drain(self, victim_key: str):
        host, _, slot = victim_key.rpartition("/")
        self._driver.administrative_drain((host, int(slot)))


class ElasticDriver:
    def __init__(self, discovery: HostDiscovery, min_np: int, max_np: int,
                 command: List[str], extra_env: Optional[dict] = None,
                 reset_limit: Optional[int] = None, verbose: bool = False,
                 discover_interval: float = DISCOVER_INTERVAL_SECS,
                 spawn_worker=None, kv_dir: Optional[str] = None,
                 kv_port: int = 0):
        self._hosts = HostManager(discovery)
        self._min_np = min_np
        self._max_np = max_np
        self._command = command
        self._extra_env = extra_env or {}
        self._reset_limit = reset_limit
        self._verbose = verbose
        self._interval = discover_interval
        # worker-spawn strategy: (hostname, rank, command, env) -> handle
        # with the WorkerProcess poll/terminate/kill surface. Schedulers
        # (Ray) inject their own placement this way; default is
        # subprocess/ssh exec.
        self._spawn_worker = spawn_worker or WorkerProcess

        # Durable control plane (ISSUE 10): with a kv_dir the rendezvous
        # KV write-ahead-logs every mutation and each driver start is a
        # new persistent control epoch — the fencing token on every
        # driver-originated command (a respawned driver's epoch outranks
        # its dead predecessor's).
        if kv_dir is None:
            kv_dir = env_str("HOROVOD_KV_DIR")
        self._kv_dir = kv_dir
        # Replicated control plane (ISSUE 19): when the supervisor runs a
        # KV replica set, the driver attaches to it through a failover
        # handle instead of embedding the server — the KV now outlives
        # the driver, and a KV-side election bumping the control epoch
        # is adopted (same incarnation) rather than treated as a rival.
        replica_eps = env_str("HOROVOD_KV_REPLICA_ENDPOINTS")
        if replica_eps:
            from horovod_tpu.runner.replica_kv import ReplicatedKVHandle
            self._kv = ReplicatedKVHandle(
                [e.strip() for e in replica_eps.split(",") if e.strip()],
                epoch_adopted=self._adopt_control_epoch).start()
        else:
            self._kv = KVServer(port=kv_port, kv_dir=kv_dir).start()
        self._epoch = self._kv.epoch
        self._registry = WorkerStateRegistry(self._kv)
        self._generation = -1
        self._prev_host_order: List[str] = []
        self._workers: Dict[Tuple[str, int], WorkerProcess] = {}
        self._host_failures: Dict[str, int] = {}
        self._failures_to_blacklist = env_int(
            "HOROVOD_FAILURES_TO_BLACKLIST", FAILURES_TO_BLACKLIST)
        self._removed_slots: set = set()
        # slot -> generation its CURRENT process was spawned into; scopes
        # the reap-time DRAINED-registry fallback so a predecessor's
        # drain record can't be charged to a respawned worker
        self._worker_spawn_gen: Dict[Tuple[str, int], int] = {}
        self._expected_slots: List[Tuple[str, int]] = []
        self._go_deadline: float = 0.0
        self._go_published: set = set()
        self._logger = get_logger("elastic.driver")
        # straggler detection over scraped worker step times; per-rank
        # scores land in the driver's registry as hvd_straggler_score /
        # hvd_straggler_flagged gauges
        self._straggler = StragglerDetector(
            k=env_float("HOROVOD_STRAGGLER_STDDEVS"),
            windows=env_int("HOROVOD_STRAGGLER_WINDOWS"),
            registry=get_registry())
        # Driver-side /metrics endpoint serving those gauges.
        # HOROVOD_DRIVER_METRICS_PORT (not the worker port family: the
        # workers already occupy HOROVOD_METRICS_PORT + local_rank on this
        # host); "0" binds ephemeral. Off by default.
        self._metrics_exporter = None
        if env_is_set("HOROVOD_DRIVER_METRICS_PORT"):
            try:
                from horovod_tpu.metrics import MetricsExporter
                self._metrics_exporter = MetricsExporter(
                    get_registry(), port=env_int("HOROVOD_DRIVER_METRICS_PORT"),
                    labels={"job": env_str("HOROVOD_JOB_NAME"),
                            "role": "elastic-driver"}).start()
                self._logger.info("driver metrics endpoint on :%d/metrics",
                                  self._metrics_exporter.port)
            except (OSError, ValueError) as e:
                self._logger.warning(
                    "driver metrics exporter disabled: %s", e)
        # (host, slot) -> last (step_count, step_seconds_sum) observed
        self._metrics_prev: Dict[Tuple[str, int], Tuple[int, float]] = {}
        # (host, slot) -> last hvd_step_anomaly_total observed (the
        # worker-side attributor's spike counter; a delta between scrapes
        # becomes a driver-level anomaly event)
        self._anomaly_prev: Dict[Tuple[str, int], float] = {}
        self.straggler_events: List[dict] = []
        # step-time anomaly events relayed from worker attributors
        self.anomaly_events: List[dict] = []
        # analyzer verdicts collected after worker failures (flight dumps)
        self.flight_verdicts: List[dict] = []
        # preemption-notice draining: slots that announced departure via
        # the KV (runner/elastic/preempt.py). Their exits are clean —
        # no failure strike, no blacklist, no flight-dump post-mortem —
        # and the announcement itself schedules a proactive resize so the
        # shard handoff lands before the host dies.
        self._draining: set = set()
        self.drain_events: List[dict] = []
        # autoscale scale-down drains: a subset of _draining whose hosts
        # stay eligible (the machine is healthy; only the slot is shed) —
        # cleared at reap so a later scale-up can respawn there
        self._admin_drains: set = set()
        # live fleet-size target the autoscaler moves within
        # [min_np, max_np]; autoscaled jobs start at the floor and earn
        # capacity from traffic, plain jobs keep the historical
        # spawn-everything behavior
        self._autoscale = env_bool("HOROVOD_AUTOSCALE")
        self._target_np = min_np if self._autoscale else max_np
        self._autoscaler = None
        if self._autoscale:
            from horovod_tpu.runner.elastic.autoscaler import Autoscaler
            self._autoscaler = Autoscaler(
                _DriverFleetOps(self), kv=self._kv, epoch=self._epoch,
                registry=get_registry())
        self._lock = threading.Lock()
        self._rebalance_needed = threading.Event()
        self._shutdown = threading.Event()
        self._result: Optional[int] = None

    def publish(self, key: str, value):
        """Seed the rendezvous KV before workers spawn (e.g. the pickled
        task function for run_task workers on shared-nothing hosts).
        Claims the control epoch like every driver-originated write, but
        leaves the payload untouched (callers own its schema)."""
        self._kv.put_json(key, value, epoch=self._epoch)

    def _publish(self, key: str, value):
        """A driver-originated command write: claims this driver's
        control epoch (the KV fences strictly-older claimants) and embeds
        it in dict payloads so workers can fence too."""
        if isinstance(value, dict):
            value = dict(value)
            value.setdefault("epoch", self._epoch)
        self._kv.put_json(key, value, epoch=self._epoch)

    def _adopt_control_epoch(self, epoch: int):
        """Replica-set callback: a KV leader election bumped the control
        epoch under this SAME driver incarnation (the handle checked the
        ``control_epoch`` ownership record). Adopt it so later driver
        writes claim the current epoch instead of fencing themselves."""
        self._epoch = max(self._epoch, int(epoch))
        if self._autoscaler is not None:
            self._autoscaler.epoch = self._epoch

    @property
    def epoch(self) -> int:
        """This driver's control epoch (bumped at every durable start)."""
        return self._epoch

    @property
    def generation(self) -> int:
        """The current (on completion: final) topology generation."""
        return self._generation

    # -- lifecycle -----------------------------------------------------------

    def run(self, start_timeout: float = 120.0, on_complete=None) -> int:
        """``on_complete(kv)`` runs after the job finishes, while the
        rendezvous KV is still alive — callers harvest worker-published
        keys (task results) there."""
        recovered = False
        if self._kv.recovered:
            try:
                recovered = self._recover()
            except Exception as e:  # noqa: BLE001 — a broken recovery
                self._log(f"driver recovery failed: {e!r}; cold-starting")
        if not recovered:
            self._wait_for_min_hosts(start_timeout)
            self._rebalance(first=True)
        poller = threading.Thread(target=self._discovery_loop, daemon=True)
        poller.start()
        barrier = threading.Thread(target=self._go_barrier_loop, daemon=True)
        barrier.start()
        try:
            return self._wait_for_completion()
        finally:
            self._shutdown.set()
            poller.join(timeout=5)
            barrier.join(timeout=5)
            if self._metrics_exporter is not None:
                self._metrics_exporter.stop()
                self._metrics_exporter = None
            self._stop_workers()
            if on_complete is not None:
                try:
                    on_complete(self._kv)
                finally:
                    self._kv.stop()
            else:
                self._kv.stop()

    def _wait_for_min_hosts(self, timeout: float):
        deadline = time.monotonic() + timeout
        while True:
            self._hosts.refresh()
            if sum(s for s in self._hosts.current.values()) >= self._min_np:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"discovery did not provide {self._min_np} slots within "
                    f"{timeout}s (have {self._hosts.current})")
            time.sleep(self._interval)

    # -- crash recovery (ISSUE 10) ------------------------------------------

    def _worker_log_path(self, key) -> Optional[str]:
        """Per-slot worker log file (durable mode only): survives the
        driver, so worker output is never lost to a control-plane crash
        and a respawned driver resumes tailing it."""
        if not self._kv_dir:
            return None
        host, local_rank = key
        return os.path.join(self._kv_dir, "logs",
                            f"{host}_{local_rank}.log")

    def _recover(self) -> bool:
        """Resume a job from WAL-recovered KV state instead of cold-
        starting generation 0: restore the current generation and its
        expected slots from the persisted topology, **adopt** workers
        that are still alive (their next heartbeats prove it — no
        double-spawn), publish the bumped control epoch, and schedule a
        rebalance only if the recovered state is incomplete (a resize or
        drain was interrupted mid-flight). Returns False to fall back to
        a cold start."""
        t0 = time.monotonic()
        gen_info = self._kv.get_json(kv_keys.generation())
        if not isinstance(gen_info, dict):
            return False
        gen = int(gen_info["generation"])
        # Even a failed adoption must keep the generation monotonic: a
        # cold start reusing g0 would resurrect stale worker_state/go
        # records as a fake READY barrier.
        self._generation = gen
        slots = []
        prefix = kv_keys.rank_and_size_prefix(gen)
        for key in self._kv.keys(prefix):
            rec = self._kv.get_json(key)
            if not isinstance(rec, dict) or rec.get("removed"):
                continue
            host, local_rank = key[len(prefix):].rsplit("/", 1)
            slots.append((int(rec.get("rank", 0)), (host, int(local_rank))))
        slots = [s for _, s in sorted(slots)]
        if not slots:
            return False
        self._expected_slots = slots
        ordered = []
        for host, _ in slots:
            if host not in ordered:
                ordered.append(host)
        self._prev_host_order = ordered
        if self._kv.get_json(kv_keys.go(gen)) is not None:
            self._go_published.add(gen)
        self._go_deadline = time.monotonic() + GO_BARRIER_TIMEOUT_SECS
        self._publish(kv_keys.control_epoch(), {"epoch": self._epoch})
        try:
            self._hosts.refresh()
        except RuntimeError as e:
            self._log(f"discovery error during recovery: {e}")
        # adopt live workers from their heartbeats (a worker that keeps
        # beating was NOT killed with the old driver; respawning it would
        # double-place the slot)
        hb_timeout = env_float("HOROVOD_WORKER_HEARTBEAT_TIMEOUT_SECONDS")
        wait_deadline = time.monotonic() + env_float(
            "HOROVOD_DRIVER_RECOVERY_WAIT_SECONDS")
        adopted: Dict[Tuple[str, int], dict] = {}
        first_beat = None
        while True:
            for key in slots:
                if key in adopted:
                    continue
                from horovod_tpu.runner.elastic.worker import heartbeat_key
                hb = self._kv.get_json(heartbeat_key(*key))
                if isinstance(hb, dict) and \
                        time.time() - float(hb.get("ts", 0)) <= hb_timeout:
                    adopted[key] = hb
                    if first_beat is None:
                        first_beat = time.monotonic()
            if len(adopted) >= len(slots) or \
                    time.monotonic() >= wait_deadline:
                break
            time.sleep(0.1)
        with self._lock:
            for key, hb in adopted.items():
                w = AdoptedWorker(key[0], hb.get("rank"),
                                  int(hb.get("pid") or 0),
                                  heartbeat_timeout=hb_timeout,
                                  log_path=self._worker_log_path(key))
                self._workers[key] = w
                self._worker_spawn_gen[key] = gen
                journal.emit("driver", "worker_adopt",
                             control_epoch=self._epoch, generation=gen,
                             host=key[0], local_rank=key[1])
        recovery_s = (first_beat or time.monotonic()) - t0
        reg = get_registry()
        reg.counter("hvd_driver_recoveries_total",
                    "driver crash recoveries completed").inc()
        reg.gauge("hvd_driver_recovery_seconds",
                  "driver start to first adopted worker heartbeat at the "
                  "last recovery").set(recovery_s)
        event = {"event": "driver_recovered", "epoch": self._epoch,
                 "generation": gen, "adopted": len(adopted),
                 "expected": len(slots),
                 "recovery_seconds": round(recovery_s, 3)}
        self._logger.warning("driver recovered: %s", json.dumps(event))
        self._log(f"driver_recovered: {json.dumps(event)}")
        journal.emit("driver", "driver_recovered", control_epoch=self._epoch,
                     generation=gen, adopted=len(adopted),
                     expected=len(slots),
                     recovery_seconds=round(recovery_s, 3))
        if len(adopted) < len(slots):
            # dead slots (or a resize/drain cut down mid-flight): the
            # normal rebalance machinery finishes the interrupted round
            self._log(f"recovery found {len(slots) - len(adopted)} dead "
                      f"slot(s); scheduling rebalance")
            self._rebalance_needed.set()
        if self._autoscaler is not None:
            # adopt fleet-size reality (the WAL's slot count outranks the
            # cold-start floor), then resume any half-finished scaling
            # decision instead of re-deciding it
            with self._lock:
                self._target_np = max(self._min_np,
                                      min(self._max_np, len(slots)))
            try:
                rec = self._autoscaler.recover()
            except Exception as e:  # noqa: BLE001 — a broken record must
                self._log(f"autoscale recovery failed: {e!r}")  # not
                # block driver recovery
                rec = None
            if rec and rec.get("action") == "down" and rec.get("victim"):
                self._resume_admin_drain(rec["victim"])
        return bool(adopted)

    def _resume_admin_drain(self, victim: str):
        """Re-apply an interrupted scale-down's driver-side accounting:
        the adopted target still counts the victim's slot (the crash beat
        the rebalance), so without this the recovered driver would
        respawn the shed slot and misread the victim's drain announce as
        a spot eviction (holding its whole healthy host out)."""
        host, _, slot = victim.rpartition("/")
        try:
            key = (host, int(slot))
        except ValueError:
            return
        with self._lock:
            if key not in self._expected_slots:
                # the pre-crash rebalance already removed the slot: the
                # adopted target excludes it, nothing to re-apply
                return
            self._target_np = max(self._target_np - 1, self._min_np)
            self._draining.add(key)
            self._admin_drains.add(key)
            self._prev_host_order = [h for h in self._prev_host_order
                                     if h != host] + [host]
        self._log(f"autoscale recovery: resumed drain of {key} "
                  f"(target fleet {self._target_np})")
        self._rebalance_needed.set()

    def _scan_heartbeats(self):
        """Refresh adopted workers' liveness from their KV heartbeats
        (remote adoptees have no pollable pid — heartbeat age is their
        only death signal)."""
        from horovod_tpu.runner.elastic.worker import heartbeat_key
        with self._lock:
            targets = [(key, w) for key, w in self._workers.items()
                       if getattr(w, "adopted", False)]
        for key, w in targets:
            hb = self._kv.get_json(heartbeat_key(*key))
            if isinstance(hb, dict):
                w.note_heartbeat(float(hb.get("ts", 0)))

    # -- discovery + rebalancing --------------------------------------------

    def _discovery_loop(self):
        while not self._shutdown.is_set():
            time.sleep(self._interval)
            # Drain scan FIRST: the refresh below must already see the
            # announced host as draining, or the same heartbeat's
            # rebalance would schedule onto a machine that is about to
            # die (and the exit of its drained worker would be misread).
            try:
                self._check_drains()
            except Exception as e:  # noqa: BLE001 — drain detection must
                self._log(f"drain scan error: {e!r}")  # not kill the driver
            try:
                changed = self._hosts.refresh()
            except RuntimeError as e:
                self._log(f"discovery error: {e}")
                continue
            self._scan_heartbeats()
            self._reap_workers()
            try:
                self._scrape_worker_metrics()
            except Exception as e:  # noqa: BLE001 — telemetry must never
                self._log(f"metrics scrape error: {e!r}")  # kill the driver
            if changed or self._rebalance_needed.is_set():
                available = sum(self._hosts.current.values())
                if available >= self._min_np:
                    self._rebalance_needed.clear()
                    self._log(f"host set changed: {self._hosts.current}")
                    self._rebalance()
                else:
                    self._log(
                        f"waiting: only {available} slots available, "
                        f"need {self._min_np}")

    def _go_barrier_loop(self):
        """Publish go/g<N> once every expected slot of generation N has
        recorded READY (reference: WorkerStateRegistry barrier,
        runner/elastic/registration.py:66-135), with a liveness fallback
        after GO_BARRIER_TIMEOUT_SECS."""
        reset_handled: set = set()
        while not self._shutdown.is_set():
            time.sleep(0.1)
            with self._lock:
                gen = self._generation
                go_out = gen in self._go_published
                expected = list(self._expected_slots)
                deadline = self._go_deadline
            if gen < 0:
                continue
            if go_out:
                # A worker that reset out of this generation (peer failure
                # without a topology change) asks for a fresh round; grant
                # it by rebalancing (reference: READY records re-triggering
                # rendezvous, registration.py:66-135).
                if gen not in reset_handled and \
                        self._kv.get_json(kv_keys.reset_request(gen)):
                    reset_handled.add(gen)
                    self._log(f"worker requested reset out of generation "
                              f"{gen}; scheduling rebalance")
                    self._rebalance_needed.set()
                continue
            counts = self._registry.count(gen, dict.fromkeys(expected))
            if counts.get(FAILURE, 0) > 0:
                # a slot already failed this generation: waiting out the
                # barrier would stall everyone for the full timeout — go
                # straight to the next topology round
                self._log(f"slot FAILURE at generation {gen} ({counts}); "
                          f"rebalancing immediately")
                with self._lock:
                    self._go_published.add(gen)  # stop polling this gen
                self._rebalance_needed.set()
                continue
            if counts.get(READY, 0) + counts.get(SUCCESS, 0) >= len(expected):
                self._log(f"all {len(expected)} slots READY at generation "
                          f"{gen}; releasing go barrier")
                # A clean generation proves its hosts healthy: clear their
                # failure counts so unrelated failures spread over hours
                # don't accumulate into a blacklisting.
                with self._lock:
                    for host in {h for h, _ in expected}:
                        self._host_failures.pop(host, None)
            elif time.monotonic() > deadline:
                self._log(f"go-barrier timeout at generation {gen} "
                          f"({counts}); releasing anyway")
            else:
                continue
            with self._lock:
                if self._generation == gen:
                    self._publish(kv_keys.go(gen), {"ts": time.time()})
                    self._go_published.add(gen)

    def _rebalance(self, first: bool = False):
        with self._lock:
            self._generation += 1
            gen = self._generation
            # Cluster-health state is per-topology: after a resize the
            # rank→host mapping shifts, so pre-resize straggler streaks and
            # step-histogram baselines would be charged to whichever rank
            # inherited the number — a healthy worker flagged on another
            # machine's history. Start every generation from a clean
            # window.
            self._straggler.reset()
            self._metrics_prev.clear()
            self._anomaly_prev.clear()
            if getattr(self, "_tiered", None) is not None:
                # consume-window floors are per-topology too: they exist
                # to protect the baselines cleared above
                self._tiered.reset()
            if self._reset_limit is not None and gen > self._reset_limit:
                self._log(f"reset limit {self._reset_limit} exceeded")
                self._result = 1
                self._shutdown.set()
                return
            # Keep prior hosts first so rank 0 lands on a worker that holds
            # committed state (reference: driver.py:232-274 keeps at least
            # one previously-used host ordered first for state sync).
            current = dict(self._hosts.current)
            # autoscale scale-down: subtract the draining slots so the
            # new topology drops exactly the victim (its host keeps its
            # other slots and stays eligible for future scale-ups)
            for host, _lr in self._admin_drains:
                if host in current:
                    current[host] -= 1
                    if current[host] <= 0:
                        del current[host]
            ordered = [h for h in self._prev_host_order if h in current]
            ordered += [h for h in sorted(current) if h not in ordered]
            self._prev_host_order = ordered
            host_list = [hosts_lib.HostInfo(h, current[h]) for h in ordered]
            slots = hosts_lib.get_host_assignments(
                host_list, min_np=min(self._min_np,
                                      sum(h.slots for h in host_list)),
                max_np=min(self._max_np, self._target_np))
            controller_host = slots[0].hostname
            controller_addr = "127.0.0.1" \
                if controller_host == "localhost" else controller_host
            controller_port, data_port = free_ports(2)
            rdv_addr = launcher_addr([s.hostname for s in slots])
            publish_assignments(self._kv, slots, controller_addr,
                                controller_port, data_port, generation=gen,
                                epoch=self._epoch)
            journal.emit("driver", "resize", control_epoch=self._epoch,
                         generation=gen, slots=len(slots),
                         hosts=len(host_list), first=bool(first))
            # mark slots no longer present as removed so resetting workers
            # on removed hosts exit cleanly (reference: gloo_context.cc
            # throws when the host is gone)
            current = {(s.hostname, s.local_rank) for s in slots}
            for key in list(self._workers):
                if key not in current:
                    self._publish(
                        kv_keys.rank_and_size(gen, key[0], key[1]),
                        {"removed": True})
                    self._removed_slots.add(key)
            # arm the READY/go barrier for this generation, then notify
            # running workers (polled inside the training process)
            self._expected_slots = [(s.hostname, s.local_rank)
                                    for s in slots]
            self._go_deadline = time.monotonic() + GO_BARRIER_TIMEOUT_SECS
            self._publish(kv_keys.notify(), {"generation": gen})
            self._publish(kv_keys.control_epoch(), {"epoch": self._epoch})
            # GC stale generations (keep the previous one: stragglers may
            # still be reading it while re-rendezvousing into gen)
            old = gen - 2
            if old >= 0:
                # prefix helpers keep the trailing "/" so g1 can't
                # swallow g10's keys; GC claims the epoch like every
                # other driver-originated mutation
                self._kv.delete_prefix(kv_keys.rank_and_size_prefix(old),
                                       epoch=self._epoch)
                self._kv.delete_prefix(kv_keys.worker_state_prefix(old),
                                       epoch=self._epoch)
                self._kv.delete_prefix(kv_keys.straggler_prefix(old),
                                       epoch=self._epoch)
                self._kv.delete_prefix(kv_keys.anomaly_prefix(old),
                                       epoch=self._epoch)
                self._kv.delete(kv_keys.go(old), epoch=self._epoch)
                self._kv.delete(kv_keys.reset_request(old),
                                epoch=self._epoch)
                self._go_published.discard(old)
            # spawn workers for slots that have no live process
            for s in slots:
                key = (s.hostname, s.local_rank)
                # a slot in the new assignment is no longer "removed", even
                # if its (re-included) process never observed the removal
                self._removed_slots.discard(key)
                if key in self._draining and \
                        not self._hosts.is_draining(s.hostname):
                    # the host survived its preemption window (or a
                    # replacement reused the name) and was genuinely
                    # re-admitted — the drain-hold expired, this is not
                    # the same heartbeat's stale host view — so clear the
                    # drain record and its KV key; the fresh worker's
                    # exits are judged normally again
                    self._draining.discard(key)
                    from horovod_tpu.runner.elastic.preempt import drain_key
                    self._kv.delete(drain_key(*key),
                                    epoch=self._epoch)
                w = self._workers.get(key)
                if w is not None and w.poll() is None:
                    continue
                env = worker_env(s, controller_addr, controller_port,
                                 data_port, self._kv.port, self._extra_env,
                                 elastic=True, generation=gen,
                                 rendezvous_addr=rdv_addr,
                                 epoch=self._epoch)
                self._log(f"spawning worker {key} (generation {gen})")
                journal.emit("driver", "worker_spawn",
                             control_epoch=self._epoch, generation=gen,
                             host=key[0], local_rank=key[1])
                self._worker_spawn_gen[key] = gen
                log_path = self._worker_log_path(key)
                if log_path is not None and \
                        self._spawn_worker is WorkerProcess:
                    # durable mode: worker output goes to a file (a pipe
                    # dies with the driver — its reader — and would EPIPE
                    # every surviving worker's next print during a crash)
                    self._workers[key] = WorkerProcess(
                        s.hostname, s.rank, self._command, env,
                        log_path=log_path)
                else:
                    self._workers[key] = self._spawn_worker(
                        s.hostname, s.rank, self._command, env)

    # -- autoscaling actuation (runner/elastic/autoscaler.py drives these) ---

    @property
    def target_np(self) -> int:
        """The live fleet-size target the autoscaler moves."""
        return self._target_np

    def request_scale_up(self):
        """Raise the fleet target one worker (clamped to max_np) and
        schedule the rebalance that spawns it."""
        with self._lock:
            self._target_np = min(self._target_np + 1, self._max_np)
            target = self._target_np
        self._log(f"autoscale: scale-up, target fleet -> {target}")
        journal.emit("driver", "scale_up", control_epoch=self._epoch,
                     generation=self._generation, target=target)
        self._rebalance_needed.set()

    def administrative_drain(self, key) -> bool:
        """Scale-down by drain, never a kill: lower the target, mark the
        slot draining (its exit is clean, the serve_targets entry flags
        ``draining`` so routers stop placing immediately), and deliver
        the preemption notice (SIGTERM) — the worker announces, finishes
        what it accepted / hands off its shard, and exits 0. Unlike a
        spot-eviction drain the HOST stays eligible: only the slot is
        shed, and a later scale-up may respawn it."""
        key = (key[0], int(key[1]))
        from horovod_tpu.runner.elastic.preempt import drain_key
        with self._lock:
            w = self._workers.get(key)
            if w is None or w.poll() is not None:
                return False
            already = key in self._admin_drains
            if key in self._draining and not already:
                # the victim is already spot-draining: a SECOND notice
                # force-exits it immediately (preempt.py), dropping its
                # acked requests — the exact hazard the spec's
                # victim_draining mutant pins
                return False
        announced = False
        if not already:
            # last-chance KV check (the reap path's pattern): the spot
            # announce may have landed after this heartbeat's drain scan
            # — the next scan will register it; we must not pile a
            # second notice on top
            try:
                announced = self._kv.get_json(drain_key(*key)) is not None
            except Exception:  # noqa: BLE001 — fall through to drain
                pass
            if announced:
                self._log(f"autoscale: {key} already announced its own "
                          f"drain; skipping the scale-down notice")
                return False
        elif self._kv.get_json(drain_key(*key)) is not None:
            # recovery re-issue, but the first notice demonstrably
            # landed (the worker announced): do not signal again
            self._rebalance_needed.set()
            return True
        with self._lock:
            if key not in self._admin_drains:
                # idempotent: a recovery-resumed decision re-issues the
                # drain after _resume_admin_drain already accounted it —
                # the notice below is re-delivered, the target is not
                # re-decremented
                self._target_np = max(self._target_np - 1, self._min_np)
                self._draining.add(key)
                self._admin_drains.add(key)
                # demote the victim's host to the back of the placement
                # order: once the drain is reaped, a shrunken assignment
                # must keep the still-running workers, not respawn on the
                # freshly shed host while dropping a healthy one
                self._prev_host_order = [h for h in self._prev_host_order
                                         if h != key[0]] + [key[0]]
            target = self._target_np
        self._log(f"autoscale: draining {key} (target fleet {target})")
        journal.emit("driver", "admin_drain", control_epoch=self._epoch,
                     generation=self._generation, host=key[0],
                     local_rank=key[1], target=target)
        try:
            w.terminate()  # the preemption notice, not a kill
        except Exception as e:  # noqa: BLE001 — the rebalance still
            self._log(f"drain signal failed: {e!r}")  # removes the slot
        self._rebalance_needed.set()
        return True

    def _check_drains(self):
        """One heartbeat's drain scan: a worker that received a preemption
        notice announces it under ``drain/<host>/<slot>`` (preempt.py).
        First sighting holds the host out of future topologies and
        schedules a proactive resize — the goal is to complete the shard
        handoff + rebalance BEFORE the machine dies, not after."""
        from horovod_tpu.runner.elastic.preempt import drain_key
        with self._lock:
            slots = list(self._expected_slots)
        for host, local_rank in slots:
            key = (host, local_rank)
            if key in self._draining:
                continue
            info = self._kv.get_json(drain_key(host, local_rank))
            if not isinstance(info, dict):
                continue
            self._register_drain(key, info.get("generation"))

    def _register_drain(self, key, announced_generation):
        """Shared drain bookkeeping for the heartbeat scan and the reap
        path's late detection: hold the host out, emit the structured
        event + counter, schedule the proactive resize."""
        host, local_rank = key
        with self._lock:
            if key in self._draining:
                return
            self._draining.add(key)
            gen = self._generation
        self._hosts.drain(host)
        event = {
            "event": "preempt_drain",
            "host": host,
            "local_rank": local_rank,
            "announced_generation": announced_generation,
            "generation": gen,
        }
        self.drain_events.append(event)
        get_registry().counter(
            "hvd_elastic_drains_total",
            "preemption-notice drains observed by the driver").inc()
        self._logger.warning("preemption drain: %s", json.dumps(event))
        journal.emit("driver", "preempt_drain", control_epoch=self._epoch,
                     generation=gen, host=host, local_rank=local_rank,
                     announced_generation=announced_generation)
        self._log(f"drain announced by {host}/{local_rank}; "
                  f"scheduling proactive resize")
        self._rebalance_needed.set()

    def _reap_workers(self):
        failed = []
        late_drains = []  # drains detected at reap time, registered below
        with self._lock:
            for key, w in list(self._workers.items()):
                code = w.poll()
                if code is None:
                    continue
                host, local_rank = key
                if code != 0 and getattr(w, "adopted", False):
                    # an adopted process's exit code is unknowable (no
                    # child handle) — the worker-state registry record is
                    # the truth for clean departures
                    from horovod_tpu.runner.elastic.registration import \
                        DRAINED
                    spawn_gen = self._worker_spawn_gen.get(key, 0)
                    for g in (self._generation, self._generation - 1):
                        if g >= spawn_gen and self._registry.get(
                                g, host, local_rank) in (SUCCESS, DRAINED):
                            code = 0
                            break
                if key in self._draining:
                    # exit-by-drain is a clean departure whatever the exit
                    # code (SIGTERM'd processes often report 143): no
                    # failure strike, no blacklist, no flight-dump
                    # post-mortem — the drain announcement already
                    # scheduled the resize
                    self._log(f"drained worker {key} exited (code {code})")
                    journal.emit("driver", "worker_exit",
                                 control_epoch=self._epoch,
                                 generation=self._generation, host=host,
                                 local_rank=local_rank, reason="drained",
                                 exit_code=code)
                    del self._workers[key]
                    self._removed_slots.discard(key)
                    if key in self._admin_drains:
                        # autoscale drain complete: clear the records so
                        # the host's slot is assignable again at the next
                        # scale-up (a spot drain keeps its hold — that
                        # machine is expected to die)
                        self._admin_drains.discard(key)
                        self._draining.discard(key)
                        from horovod_tpu.runner.elastic.preempt import \
                            drain_key
                        try:
                            self._kv.delete(drain_key(*key),
                                            epoch=self._epoch)
                        except Exception:  # noqa: BLE001 — best effort
                            pass
                    continue
                if code == 0:
                    if key in self._removed_slots:
                        # a slot dropped by a scale-down exits cleanly; it
                        # is not a job-completion signal
                        self._log(f"removed worker {key} exited")
                        journal.emit("driver", "worker_exit",
                                     control_epoch=self._epoch,
                                     generation=self._generation,
                                     host=host, local_rank=local_rank,
                                     reason="removed", exit_code=code)
                        del self._workers[key]
                        self._removed_slots.discard(key)
                        continue
                    # last-chance drain check: a worker that announced and
                    # exited within one heartbeat may beat the drain scan
                    # to this reap — its clean exit must not read as job
                    # completion. Two signals, either suffices: the KV
                    # drain key (written async, may not have landed) and
                    # the DRAINED registry record (written synchronously
                    # right before the exit, at the worker's own
                    # generation, which may trail the driver's by one).
                    # The registry probe is scoped to generations at or
                    # after THIS process's spawn — a respawned worker must
                    # not inherit its drained predecessor's record, or a
                    # successful completion reads as a drain.
                    from horovod_tpu.runner.elastic.preempt import drain_key
                    drained = bool(self._kv.get_json(
                        drain_key(host, local_rank)))
                    if not drained:
                        from horovod_tpu.runner.elastic.registration \
                            import DRAINED
                        spawn_gen = self._worker_spawn_gen.get(key, 0)
                        for g in (self._generation, self._generation - 1):
                            if g >= spawn_gen and self._registry.get(
                                    g, host, local_rank) == DRAINED:
                                drained = True
                                break
                    if drained:
                        self._log(f"worker {key} exited after drain "
                                  f"announcement; treating as drain")
                        del self._workers[key]
                        late_drains.append(key)
                        continue
                    self._log(f"worker {key} finished successfully")
                    journal.emit("driver", "worker_exit",
                                 control_epoch=self._epoch,
                                 generation=self._generation, host=host,
                                 local_rank=local_rank, reason="success",
                                 exit_code=0)
                    self._result = 0 if self._result is None else self._result
                    self._shutdown.set()
                    continue
                self._log(f"worker {key} failed with code {code}")
                journal.emit("driver", "worker_exit",
                             control_epoch=self._epoch,
                             generation=self._generation, host=host,
                             local_rank=local_rank, reason="failure",
                             exit_code=code)
                del self._workers[key]
                failed.append((key, code))
                self._host_failures[host] = \
                    self._host_failures.get(host, 0) + 1
                if self._host_failures[host] >= self._failures_to_blacklist:
                    self._log(f"blacklisting {host} (cooldown applies — "
                              f"see HOROVOD_BLACKLIST_COOLDOWN_SECONDS)")
                    self._hosts.blacklist(host)
                    journal.emit("driver", "host_blacklist",
                                 control_epoch=self._epoch,
                                 generation=self._generation, host=host,
                                 failures=self._failures_to_blacklist)
                    self._host_failures.pop(host, None)
                # request an explicit rebalance (respawns the dead slot at a
                # fresh generation); replaces the prior hack of clearing the
                # discovery view, which raced with the discovery thread
                self._rebalance_needed.set()
        # Late drains register outside the lock (_register_drain takes it)
        # so the doomed host is held out and the proactive resize fires
        # even when the exit beat the heartbeat's drain scan.
        for key in late_drains:
            self._register_drain(key, None)
        # Dump collection polls the filesystem for up to 1.5s — done once
        # for the whole reap pass (several workers dying together are one
        # incident) and outside the lock so the go-barrier, rebalance, and
        # metrics threads aren't frozen while post-mortems are gathered.
        if failed:
            self._collect_flight_dumps(failed)

    def _collect_flight_dumps(self, failed):
        """Post-mortem hook: when workers die (``failed`` = this reap
        pass's [(key, exit_code), ...]) and the job runs with
        ``HOROVOD_FLIGHT_DIR``, every surviving rank's engine writes a
        flight dump during the fast abort that follows. Collect them and
        log the cross-rank analyzer's verdict (which rank died, which
        tensor was in flight) next to the failure itself, so the operator
        never has to reconstruct the last seconds by hand."""
        flight_dir = (self._extra_env.get("HOROVOD_FLIGHT_DIR") or
                      env_str("HOROVOD_FLIGHT_DIR"))
        if not flight_dir:
            return
        try:
            from horovod_tpu.profiler import flight
            # Survivors dump within one coordination cycle of the death,
            # and the driver notices the exit on its ~1s heartbeat — so
            # this incident's dumps are at most a few seconds old. Dumps
            # older than that window are leftovers of an earlier trigger
            # (files are overwritten in place, never cleaned); analyzing
            # them would describe the wrong incident. Wait briefly for
            # fresh files to land (write-then-rename keeps them whole).
            freshness_us = 30e6
            deadline = time.monotonic() + 1.5
            dumps = {}
            while time.monotonic() < deadline:
                dumps = {
                    r: d
                    for r, d in flight.load_dumps(flight_dir).items()
                    if time.time() * 1e6 - d.get("dump_unix_us", 0)
                    < freshness_us}
                if dumps:
                    # don't analyze a partial set: a survivor whose dump
                    # hasn't landed yet would be reported dead. Dying
                    # ranks often dump too (the abort path runs before
                    # exit), so a count net of the dead can be satisfied
                    # while a slow survivor is still writing — only a
                    # dump from EVERY rank ends the wait early; anything
                    # less polls to the deadline.
                    expect = max(int(d.get("size", 0))
                                 for d in dumps.values())
                    if len(dumps) >= max(expect, 1):
                        break
                time.sleep(0.1)
            if not dumps:
                self._log(f"worker(s) {sorted(k for k, _ in failed)} failed "
                          f"(codes {[c for _, c in failed]}) but no fresh "
                          f"flight dumps appeared in {flight_dir}")
                return
            verdict = flight.analyze(dumps)
            self.flight_verdicts.append(verdict)
            journal.emit("driver", "flight_verdict",
                         control_epoch=self._epoch,
                         generation=self._generation,
                         dead_ranks=verdict.get("dead_ranks"),
                         desync=verdict.get("desync"),
                         lagging_rank=verdict.get("lagging_rank"),
                         failed=sorted(f"{k[0]}/{k[1]}"
                                       for k, _ in failed))
            for line in verdict["lines"]:
                self._logger.warning("flight analyzer: %s", line)
                self._log(f"flight analyzer: {line}")
        except Exception as e:  # noqa: BLE001 — post-mortem analysis must
            self._log(f"flight-dump collection failed: {e!r}")  # not kill
            # the driver

    # -- cluster health (metrics scrape + straggler detection) --------------

    def _scrape_worker_metrics(self):
        """One heartbeat window over the tiered telemetry plane: for each
        expected host, consume the per-host aggregator's ``/agg.json``
        (endpoint published under ``agg_addr/<host>`` by local_rank 0's
        exporter) when fresh, or fall back to the per-rank
        ``/metrics.json`` scrape (endpoints under
        ``metrics_addr/<host>/<slot>``) when the aggregator is dead or
        stale — O(hosts) HTTP round-trips on the happy path instead of
        O(ranks). Both paths diff the step-time histogram and
        ``hvd_step_anomaly_total`` against the same baseline maps (see
        :class:`horovod_tpu.metrics.aggregator.TieredScrape`), so counter
        deltas stay monotonic across an aggregator death + fallback and a
        rank is never double-counted within a heartbeat. Workers without
        an exporter (metrics off) are simply absent.

        Side outputs of the same pass: the scrape-target list is published
        to the KV under ``metrics_targets`` (what ``hvd-top --kv`` reads to
        discover the cluster) and the live aggregator list under
        ``agg_targets`` (what hvd-top's host rollup prefers), and each
        worker's anomaly-counter delta surfaces as a driver-level
        structured event."""
        with self._lock:
            slots = list(self._expected_slots)
            gen = self._generation
        serve_targets: List[dict] = []
        for host, local_rank in slots:
            # serving plane: aggregate worker-published serve endpoints
            # into one key (the ingress router's discovery input — the
            # serving analog of metrics_targets below)
            sinfo = self._kv.get_json(kv_keys.serve_addr(host, local_rank))
            if isinstance(sinfo, dict) and sinfo.get("addr") \
                    and sinfo.get("port"):
                entry = {"id": sinfo.get("id") or f"{host}/{local_rank}",
                         "addr": sinfo["addr"], "port": sinfo["port"],
                         "rank": sinfo.get("rank"),
                         "generation": sinfo.get("generation")}
                if (host, local_rank) in self._draining:
                    # scale-down announce: routers stop placing NEW
                    # requests on this worker the moment they see the
                    # table, not once the worker finally leaves it
                    entry["draining"] = True
                serve_targets.append(entry)
        if getattr(self, "_tiered", None) is None:
            # one instance across heartbeats: it carries the per-host
            # consume-window floors that keep the two paths ordered
            self._tiered = TieredScrape(self._kv.get_json)
        result = self._tiered.heartbeat(
            slots, self._metrics_prev, self._anomaly_prev,
            want_slo=self._autoscaler is not None)
        times = result.times
        anomalies = [(key, info, delta)
                     for key, info, delta in result.anomalies]
        serve_slos = result.slos
        if result.targets:
            try:
                self._publish(kv_keys.metrics_targets(), result.targets)
            except Exception:  # noqa: BLE001 — telemetry must not kill
                pass  # the heartbeat
        if result.agg_targets or getattr(self, "_agg_published", False):
            # same empty-table contract as serve_targets: once any
            # aggregator has registered, an empty list means "all
            # aggregators gone — scrape direct", not "no information"
            self._agg_published = True
            try:
                self._publish(kv_keys.agg_targets(),
                              {"generation": gen,
                               "hosts": result.agg_targets})
            except Exception:  # noqa: BLE001
                pass
        if serve_targets or getattr(self, "_serve_published", False):
            # keep publishing once any serve worker has ever registered:
            # an EMPTY table is routing information too (all workers gone
            # -> ingress routers must drain, not keep a stale set), while
            # pure-training jobs never touch the key
            self._serve_published = True
            try:
                # epoch-claimed: a fenced-out stale driver must not be
                # able to publish a shrunken fleet and drain the routers
                self._publish(kv_keys.serve_targets(),
                              {"generation": gen,
                               "workers": serve_targets})
            except Exception:  # noqa: BLE001 — routing discovery must not
                pass  # kill the heartbeat either
        for key, info, delta in anomalies:
            self._ingest_anomaly(key, info, delta)
        if times:
            self._ingest_step_times(times)
        if self._autoscaler is not None and serve_slos:
            draining_keys = [f"{h}/{lr}" for h, lr in self._draining]
            try:
                self._autoscaler.tick(serve_slos, draining_keys)
            except Exception as e:  # noqa: BLE001 — policy errors must
                self._log(f"autoscale tick error: {e!r}")  # not kill the
                # heartbeat

    def _ingest_anomaly(self, key: Tuple[str, int], info: dict,
                        delta: float):
        """Relay a worker attributor's step-time spike (counter delta
        between scrapes) as a driver-level structured event: logged,
        appended to :attr:`anomaly_events`, published under
        ``anomaly/g<N>/<rank>``. Split from the scraper so tests can drive
        it without HTTP."""
        with self._lock:
            gen = self._generation
        event = {
            "event": "step_anomaly",
            "rank": info.get("rank"),
            "host": key[0],
            "local_rank": key[1],
            "new_anomalies": int(delta),
            "generation": gen,
        }
        self.anomaly_events.append(event)
        self._logger.warning("worker step anomaly: %s", json.dumps(event))
        journal.emit("driver", "step_anomaly", control_epoch=self._epoch,
                     generation=gen, rank=event["rank"], host=key[0],
                     local_rank=key[1], new_anomalies=int(delta))
        self._log(f"anomaly event: {json.dumps(event)}")
        try:
            self._kv.put_json(kv_keys.anomaly(gen, event["rank"]), event,
                              epoch=self._epoch)
        except Exception:  # noqa: BLE001
            pass

    def _ingest_step_times(self, step_times: Dict[int, float]):
        """Feed one window of per-rank mean step times; log/publish the
        structured events that fire. Split from the scraper so tests can
        drive the detection without processes or HTTP."""
        for event in self._straggler.update(step_times):
            with self._lock:
                event["generation"] = self._generation
            self.straggler_events.append(event)
            self._logger.warning("straggler detected: %s",
                                 json.dumps(event))
            journal.emit("driver", "straggler", control_epoch=self._epoch,
                         generation=event.get("generation"),
                         rank=event.get("rank"),
                         step_time_sec=event.get("step_time_sec"),
                         median_sec=event.get("median_sec"))
            self._log(f"straggler event: {json.dumps(event)}")
            try:
                self._kv.put_json(
                    kv_keys.straggler(event["generation"], event["rank"]),
                    event, epoch=self._epoch)
            except Exception:  # noqa: BLE001
                pass

    def _wait_for_completion(self) -> int:
        while not self._shutdown.is_set():
            time.sleep(0.2)
        # drain remaining workers briefly
        deadline = time.monotonic() + 30
        for w in self._workers.values():
            remaining = max(0.1, deadline - time.monotonic())
            try:
                w.wait(timeout=remaining)
            except Exception:  # noqa: BLE001
                w.terminate()
        return self._result if self._result is not None else 1

    def _stop_workers(self, grace: float = 5.0):
        """Teardown kill with escalation. SIGTERM alone no longer
        guarantees death: elastic workers install the preemption-notice
        handler, which defers exit to the next commit boundary (a second
        SIGTERM force-exits, but a worker wedged in a peerless collective
        may never run Python again) — so any survivor of the grace window
        is SIGKILLed rather than left orphaned on the host."""
        for w in self._workers.values():
            w.terminate()
        deadline = time.monotonic() + grace
        for w in self._workers.values():
            if w.poll() is not None:
                continue
            try:
                w.wait(timeout=max(0.1, deadline - time.monotonic()))
            except Exception:  # noqa: BLE001 — escalate below
                pass
        for w in self._workers.values():
            if w.poll() is None:
                self._log("worker survived SIGTERM grace; killing")
                w.kill()

    def _log(self, msg: str):
        # route through the HOROVOD_LOG_LEVEL-configured logger; --verbose
        # keeps the historical always-on stderr stream for the launcher UX
        self._logger.info(msg)
        if self._verbose:
            sys.stderr.write(f"[elastic-driver] {msg}\n")
            sys.stderr.flush()
