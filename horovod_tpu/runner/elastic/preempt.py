"""Preemption-notice draining for elastic workers.

Spot/preemptible pools deliver an eviction warning (SIGTERM, typically
30-120s before the machine dies). Without a drain path that warning is
wasted: the kill looks like a crash, the survivors fast-abort, the host is
charged a failure, and under ZeRO-1 the dead rank's optimizer-state shard
is simply gone. The drain protocol turns the warning into a clean resize:

1. the signal handler marks the worker ``draining`` and announces it on
   the rendezvous KV (``drain/<host>/<slot>``) — the driver sees the
   announcement on its next heartbeat and schedules a proactive rebalance
   that excludes the doomed slot (no blacklist, no abort storm);
2. the in-flight training step finishes normally — the drain only takes
   effect at the next ``State.commit()`` boundary, where live state is
   self-consistent;
3. the worker hands off its live ZeRO shard to the KV
   (``shard_handoff/w<world>/<rank>``, int8-compressed when
   ``HOROVOD_RESHARD_COMPRESSION=int8``) so the post-resize
   ``ShardedState.sync()`` resumes with ZERO state loss;
4. the worker records ``DRAINED`` in the worker-state registry and exits
   0 — ``ElasticDriver._reap_workers`` treats any exit of an announced
   drain as clean departure.

Everything here is best-effort by design: a preempted machine may die
mid-handoff, in which case the resize falls back to the ring-buddy replica
(see jax/elastic.ShardedState) or fresh moments for that slice.
"""

from __future__ import annotations

import base64
import signal
import threading
import time
from typing import Optional

from horovod_tpu.common import journal
from horovod_tpu.common.env_registry import env_bool, env_str
from horovod_tpu.common.hvd_logging import get_logger

_logger = get_logger("elastic.preempt")

_lock = threading.Lock()
_installed = False
_requested = threading.Event()
_drained = threading.Event()


def drain_key(host: str, slot) -> str:
    """KV key a worker announces its departure under — shared single
    definition with the driver's heartbeat scan (typed registry:
    common/kv_keys.py)."""
    from horovod_tpu.common import kv_keys
    return kv_keys.drain(host, slot)


def handoff_key(world: int, old_rank: int) -> str:
    """KV key for a departing rank's live shard payload, scoped by the
    shard layout's world size (the consuming sync knows the old world from
    the survivor descriptors, not the drain generation)."""
    from horovod_tpu.common import kv_keys
    return kv_keys.shard_handoff(world, old_rank)


def preempt_requested() -> bool:
    """True once a preemption notice has been received (the worker should
    drain at the next commit boundary)."""
    return _requested.is_set()


def request_preemption():
    """Mark this worker as preempted and announce the drain on the KV.

    Called by the signal handler, but also directly by tests and by
    schedulers that learn about eviction through an API rather than a
    signal."""
    if _requested.is_set():
        return
    _requested.set()
    # The KV announcement leaves the signal context immediately: HTTP from
    # a handler risks re-entrancy, and the put must retry.
    threading.Thread(target=_announce, daemon=True).start()


def _on_preempt_signal(*_):
    # A REPEATED notice forces immediate exit: the first one starts the
    # graceful drain, but the sender (the platform's grace-expired kill,
    # or the elastic driver's own teardown killpg) must still be able to
    # stop a worker that never reaches a commit boundary.
    if _requested.is_set():
        import os
        os._exit(143)
    request_preemption()


def _announce():
    from horovod_tpu.runner.elastic import worker as elastic_worker
    if not elastic_worker.is_elastic_worker():
        return
    host, slot = elastic_worker._slot()
    payload = {
        "generation": elastic_worker.current_generation(),
        "ts": time.time(),
    }
    try:
        elastic_worker.kv_client().put_json(drain_key(host, slot), payload,
                                            deadline=10.0)
        _logger.warning("preemption notice: announced drain for %s/%s",
                        host, slot)
        journal.emit("worker", "drain_announce",
                     generation=payload["generation"], host=host,
                     local_rank=slot)
    except Exception as e:  # noqa: BLE001 — the driver also sees the exit
        # headless mode (driver mid-restart): queue the announcement so
        # the heartbeat thread replays it the moment the KV returns
        from horovod_tpu.runner.elastic import headless
        headless.queue_write(drain_key(host, slot), payload)
        _logger.warning("drain announcement failed (%r); queued for "
                        "replay on driver reconnect", e)


def install_preempt_handler(sig: Optional[str] = None) -> bool:
    """Install the preemption-notice handler (idempotent; main thread
    only — signal.signal raises elsewhere, in which case the caller polls
    ``request_preemption`` through other means). Returns True when
    installed."""
    global _installed
    with _lock:
        if _installed:
            return True
        name = sig or env_str("HOROVOD_PREEMPT_SIGNAL")
        signum = getattr(signal, name, None)
        if signum is None:
            _logger.warning("unknown HOROVOD_PREEMPT_SIGNAL %r", name)
            return False
        try:
            signal.signal(signum, _on_preempt_signal)
        except ValueError:  # not the main thread
            return False
        _installed = True
        return True


def _reset_for_tests():
    global _installed
    with _lock:
        _installed = False
        _requested.clear()
        _drained.clear()


# -- shard handoff (step 3) -------------------------------------------------


def encode_shard_stacks(stacks: dict, quantized: bool = False) -> dict:
    """JSON-safe encoding of ``{name: {group: [rows, shard] array}}`` —
    the KV transports base64 blobs. With ``quantized`` float payloads ride
    the block-int8 codec (scales + values), ~4x smaller on the wire."""
    import numpy as np
    from horovod_tpu.parallel import zero
    out = {}
    for name, groups in stacks.items():
        enc = {}
        for key, arr in groups.items():
            arr = np.asarray(arr)
            entry = {"dtype": str(arr.dtype), "rows": int(arr.shape[0]),
                     "cols": int(arr.shape[1])}
            if quantized and arr.dtype.kind == "f":
                q, scales = zero.quantize_blocks_np(arr.ravel())
                entry["codec"] = "int8"
                entry["b64"] = base64.b64encode(q.tobytes()).decode()
                entry["scales_b64"] = base64.b64encode(
                    scales.tobytes()).decode()
            else:
                entry["codec"] = "raw"
                entry["b64"] = base64.b64encode(
                    np.ascontiguousarray(arr).tobytes()).decode()
            enc[key] = entry
        out[name] = enc
    return out


def decode_shard_stacks(payload: dict) -> dict:
    import numpy as np
    from horovod_tpu.parallel import zero
    out = {}
    for name, groups in payload.items():
        dec = {}
        for key, entry in groups.items():
            dtype = np.dtype(entry["dtype"])
            rows, cols = int(entry["rows"]), int(entry["cols"])
            raw = base64.b64decode(entry["b64"])
            if entry.get("codec") == "int8":
                q = np.frombuffer(raw, np.int8)
                scales = np.frombuffer(
                    base64.b64decode(entry["scales_b64"]), np.float32)
                flat = zero.dequantize_blocks_np(q, scales, dtype)
            else:
                flat = np.frombuffer(raw, dtype)
            dec[key] = flat.reshape(rows, cols).copy()
        out[name] = dec
    return out


def publish_handoff(world: int, old_rank: int, stacks: dict,
                    client=None) -> bool:
    """Publish a departing rank's live shard stacks to the KV. Returns
    False (without raising) when the handoff could not land — the resize
    then falls back to buddy replicas."""
    if not env_bool("HOROVOD_PREEMPT_HANDOFF"):
        return False
    from horovod_tpu.runner.elastic import worker as elastic_worker
    quantized = env_str("HOROVOD_RESHARD_COMPRESSION") == "int8"
    payload = {
        "world": int(world),
        "old_rank": int(old_rank),
        "quantized": quantized,
        "ts": time.time(),
        "stacks": encode_shard_stacks(stacks, quantized),
    }
    try:
        (client or elastic_worker.kv_client()).put_json(
            handoff_key(world, old_rank), payload, deadline=20.0)
        return True
    except Exception as e:  # noqa: BLE001 — machine may die any moment
        # best-effort replay if the process survives until the KV is
        # back; the caller still treats this handoff as not-landed (the
        # resize falls back to the buddy replica, and fetch_handoff's
        # TTL rejects a too-late replay)
        from horovod_tpu.runner.elastic import headless
        headless.queue_write(handoff_key(world, old_rank), payload)
        _logger.warning("shard handoff failed (%r); queued for replay", e)
        return False


def fetch_handoff(world: int, old_rank: int, client=None) -> Optional[dict]:
    """The decoded ``{name: {group: [rows, shard]}}`` stacks a drained
    rank left behind, or None.

    Stale payloads are rejected: a handoff is only meaningful for the
    resize that immediately follows its drain — an hours-old key (e.g.
    one a scale-to-one consumer failed to GC) must not outrank a fresh
    buddy replica in the source-assignment preference."""
    from horovod_tpu.common.env_registry import env_float
    from horovod_tpu.runner.elastic import worker as elastic_worker
    try:
        # Short deadline, not the KV's rendezvous-style 5s poll: a
        # handoff either landed before the resize began (the drain
        # published it before exiting) or it never will (hard kill) —
        # and every missing rank's probe holds ALL peers inside the
        # offers collective, so long 404 polling here multiplies
        # straight into recovery time.
        payload = (client or elastic_worker.kv_client()).get_json(
            handoff_key(world, old_rank), timeout=1.0, poll_interval=0.4)
    except Exception:  # noqa: BLE001 — KV may be restarting
        return None
    if not isinstance(payload, dict) or "stacks" not in payload:
        return None
    ttl = env_float("HOROVOD_PREEMPT_COOLDOWN_SECONDS")
    if ttl <= 0:
        ttl = 600.0
    if time.time() - float(payload.get("ts", 0)) > ttl:
        return None
    return decode_shard_stacks(payload["stacks"])


def finalize_drain(state=None):
    """Complete the drain at a safe (commit) boundary: hand off the live
    shard, record DRAINED, exit cleanly. Raises SystemExit(0)."""
    from horovod_tpu.runner.elastic import worker as elastic_worker
    if _drained.is_set():
        raise SystemExit(0)
    _drained.set()
    if elastic_worker.is_elastic_worker():
        payload_fn = getattr(state, "shard_handoff_payload", None)
        if callable(payload_fn):
            try:
                world, old_rank, data = payload_fn()
                if data:
                    publish_handoff(world, old_rank, data)
            except Exception as e:  # noqa: BLE001 — best effort
                _logger.warning("handoff skipped: %r", e)
        try:
            elastic_worker.record_state(
                elastic_worker.current_generation(),
                elastic_worker.DRAINED)
        except Exception:  # noqa: BLE001 — the exit code still says clean
            pass
    _logger.warning("drain complete; exiting cleanly")
    journal.emit("worker", "drain_finalize")
    raise SystemExit(0)
