"""Elastic host discovery.

Reference analog: horovod/runner/elastic/discovery.py — HostDiscovery
(script-driven membership) + HostManager with blacklist (:41-47,102-108).
The discovery script prints one "hostname:slots" line per available host;
the driver polls it every second (reference: driver.py:181-201).
"""

from __future__ import annotations

import subprocess
import threading
import time
from typing import Dict, List

from horovod_tpu.common.env_registry import env_float
from horovod_tpu.runner import hosts as hosts_lib

# A blacklisted host becomes eligible again after this long and is
# re-probed — transient failures (OOM kill, preemption, a flapping NIC)
# must not permanently shrink the job the way the reference's
# forever-blacklist does (reference: discovery.py HostManager). 0 or
# negative restores the permanent behavior.
DEFAULT_BLACKLIST_COOLDOWN_SECONDS = 300.0


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    def __init__(self, script: str):
        self._script = script

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.run(self._script, shell=True, capture_output=True,
                             text=True, timeout=30)
        if out.returncode != 0:
            raise RuntimeError(
                f"host discovery script failed (rc={out.returncode}): "
                f"{out.stderr.strip()}")
        hosts: Dict[str, int] = {}
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line:
                continue
            info = hosts_lib.HostInfo.from_string(line)
            hosts[info.hostname] = info.slots
        return hosts


class FixedHostDiscovery(HostDiscovery):
    """Static membership (for tests / driving the state machine manually)."""

    def __init__(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)

    def update(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)


class HostManager:
    """Tracks current hosts + blacklist with cooldown (reference:
    discovery.py HostManager, which blacklists forever; here a blacklisted
    host becomes eligible again after HOROVOD_BLACKLIST_COOLDOWN_SECONDS
    and is re-probed at the next refresh)."""

    def __init__(self, discovery: HostDiscovery,
                 cooldown: float = None, drain_cooldown: float = None):
        self._discovery = discovery
        self._lock = threading.Lock()
        # hostname -> monotonic timestamp of the (latest) blacklisting
        self._blacklist: Dict[str, float] = {}
        # hostname -> monotonic timestamp of the drain announcement.
        # Distinct from the blacklist on purpose: a drained host did
        # nothing wrong (no failure strikes, no post-mortem) — it is
        # simply expected to die. Held out until discovery stops listing
        # it or HOROVOD_PREEMPT_COOLDOWN_SECONDS passes (a replacement
        # spot instance may reuse the name).
        self._draining: Dict[str, float] = {}
        self.current: Dict[str, int] = {}
        if cooldown is None:
            cooldown = env_float("HOROVOD_BLACKLIST_COOLDOWN_SECONDS",
                                 DEFAULT_BLACKLIST_COOLDOWN_SECONDS)
        self._cooldown = cooldown
        if drain_cooldown is None:
            drain_cooldown = env_float("HOROVOD_PREEMPT_COOLDOWN_SECONDS")
        self._drain_cooldown = drain_cooldown

    def blacklist(self, hostname: str):
        with self._lock:
            self._blacklist[hostname] = time.monotonic()

    def drain(self, hostname: str):
        """Hold a host out of future topologies after a preemption notice
        (no blacklist strike; re-admitted after the drain cooldown)."""
        with self._lock:
            self._draining[hostname] = time.monotonic()

    def is_draining(self, hostname: str) -> bool:
        with self._lock:
            ts = self._draining.get(hostname)
            if ts is None:
                return False
            if self._drain_expired(ts):
                del self._draining[hostname]
                return False
            return True

    def _drain_expired(self, ts: float) -> bool:
        return self._drain_cooldown > 0 and \
            time.monotonic() - ts >= self._drain_cooldown

    def _expired(self, ts: float) -> bool:
        return self._cooldown > 0 and \
            time.monotonic() - ts >= self._cooldown

    def is_blacklisted(self, hostname: str) -> bool:
        with self._lock:
            ts = self._blacklist.get(hostname)
            if ts is None:
                return False
            if self._expired(ts):
                # cooldown elapsed: forget the entry so the host is
                # re-probed; a repeat failure re-blacklists it afresh
                del self._blacklist[hostname]
                return False
            return True

    def refresh(self) -> bool:
        """Poll discovery; returns True if the usable host set changed."""
        found = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            for h in [h for h, ts in self._blacklist.items()
                      if self._expired(ts)]:
                del self._blacklist[h]
            for h in [h for h, ts in self._draining.items()
                      if self._drain_expired(ts) or
                      (self._drain_cooldown <= 0 and h not in found)]:
                # re-admit strictly by cooldown (a single transient
                # discovery blip must not re-admit a machine that is
                # about to die); with the cooldown disabled (<=0) the
                # hold instead lifts when discovery stops listing the
                # host (the preemption completed)
                del self._draining[h]
            usable = {h: s for h, s in found.items()
                      if h not in self._blacklist
                      and h not in self._draining}
        changed = usable != self.current
        self.current = usable
        return changed

    def host_list(self) -> List[hosts_lib.HostInfo]:
        return [hosts_lib.HostInfo(h, s)
                for h, s in sorted(self.current.items())]
