"""Elastic host discovery.

Reference analog: horovod/runner/elastic/discovery.py — HostDiscovery
(script-driven membership) + HostManager with blacklist (:41-47,102-108).
The discovery script prints one "hostname:slots" line per available host;
the driver polls it every second (reference: driver.py:181-201).
"""

from __future__ import annotations

import subprocess
import threading
from typing import Dict, List

from horovod_tpu.runner import hosts as hosts_lib


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    def __init__(self, script: str):
        self._script = script

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.run(self._script, shell=True, capture_output=True,
                             text=True, timeout=30)
        if out.returncode != 0:
            raise RuntimeError(
                f"host discovery script failed (rc={out.returncode}): "
                f"{out.stderr.strip()}")
        hosts: Dict[str, int] = {}
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line:
                continue
            info = hosts_lib.HostInfo.from_string(line)
            hosts[info.hostname] = info.slots
        return hosts


class FixedHostDiscovery(HostDiscovery):
    """Static membership (for tests / driving the state machine manually)."""

    def __init__(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)

    def update(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)


class HostManager:
    """Tracks current hosts + blacklist (reference: discovery.py
    HostManager)."""

    def __init__(self, discovery: HostDiscovery):
        self._discovery = discovery
        self._lock = threading.Lock()
        self._blacklist = set()
        self.current: Dict[str, int] = {}

    def blacklist(self, hostname: str):
        with self._lock:
            self._blacklist.add(hostname)

    def is_blacklisted(self, hostname: str) -> bool:
        with self._lock:
            return hostname in self._blacklist

    def refresh(self) -> bool:
        """Poll discovery; returns True if the usable host set changed."""
        found = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            usable = {h: s for h, s in found.items()
                      if h not in self._blacklist}
        changed = usable != self.current
        self.current = usable
        return changed

    def host_list(self) -> List[hosts_lib.HostInfo]:
        return [hosts_lib.HostInfo(h, s)
                for h, s in sorted(self.current.items())]
