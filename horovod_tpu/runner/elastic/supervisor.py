"""Driver supervision: the crash-recoverable control plane's outer loop.

The elastic driver hosts the rendezvous KV every protocol rides, so PR 9's
"self-healing" job was still one SIGKILL away from headlessness. With
``HOROVOD_KV_DIR`` set, the launcher no longer runs the driver in-process:

1. the **supervisor** (this module, inside the launcher process) spawns
   the driver as a subprocess (``python -m
   horovod_tpu.runner.elastic.supervisor --driver <args.json>``) with a
   **pre-allocated KV port** so every incarnation binds the same endpoint
   workers already hold in ``HOROVOD_RENDEZVOUS_PORT``;
2. a driver that exits *intentionally* (job finished, reset limit) writes
   a done-marker into the KV dir first — the supervisor returns its
   result;
3. any other exit (SIGKILL, OOM, crash) is a **crash**: the supervisor
   respawns after ``HOROVOD_DRIVER_RESTART_BACKOFF_SECONDS``, up to
   ``HOROVOD_DRIVER_RESTART_LIMIT`` times. The respawned driver replays
   the KV WAL, bumps the persistent control epoch, adopts still-running
   workers from their heartbeats, and finishes whatever resize/drain the
   crash interrupted (:meth:`ElasticDriver._recover`).

Workers meanwhile keep training on the peer-to-peer data plane (headless
mode, :mod:`~horovod_tpu.runner.elastic.headless`) — the control plane's
death is an observability gap, not a training outage.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

from horovod_tpu.common import journal
from horovod_tpu.common.env_registry import env_float, env_int, env_str
from horovod_tpu.common.hvd_logging import get_logger

_ARGS_FILE = "driver_args.json"
_DONE_FILE = "driver_done.json"

_logger = get_logger("elastic.supervisor")


def _done_path(kv_dir: str) -> str:
    return os.path.join(kv_dir, _DONE_FILE)


def _write_done(kv_dir: str, rc: int):
    """Mark an intentional driver exit (atomic write-then-rename) so the
    supervisor can tell 'job finished with rc' from 'driver crashed'."""
    path = _done_path(kv_dir)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump({"rc": int(rc), "pid": os.getpid(),
                       "ts": time.time()}, f)
        os.replace(tmp, path)
    except OSError as e:
        _logger.warning("could not write driver done marker: %r", e)


def _read_done(kv_dir: str, pid: int) -> Optional[int]:
    """The marker's rc if it was written by driver incarnation ``pid``."""
    try:
        with open(_done_path(kv_dir)) as f:
            doc = json.load(f)
        return int(doc["rc"]) if int(doc.get("pid", -1)) == pid else None
    except (OSError, ValueError, KeyError):
        return None


def driver_main(args_path: str) -> int:
    """One driver incarnation (the ``--driver`` subprocess entry): run
    the ElasticDriver over the durable KV, then write the done marker so
    the supervising launcher knows this exit was intentional."""
    from horovod_tpu.common.hvd_logging import setup_python_logging
    from horovod_tpu.runner.elastic.discovery import HostDiscoveryScript
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    setup_python_logging()
    with open(args_path) as f:
        payload = json.load(f)
    kv_dir = env_str("HOROVOD_KV_DIR")
    driver = ElasticDriver(
        discovery=HostDiscoveryScript(payload["host_discovery_script"]),
        min_np=payload["min_np"], max_np=payload["max_np"],
        command=payload["command"], extra_env=payload.get("extra_env"),
        reset_limit=payload.get("reset_limit"),
        verbose=payload.get("verbose", False),
        kv_dir=kv_dir, kv_port=payload.get("kv_port", 0))
    rc = driver.run(start_timeout=payload.get("start_timeout", 120.0))
    if kv_dir:
        _write_done(kv_dir, rc)
    return rc


def run_supervised(args) -> int:
    """The launcher-side supervisor loop (``run_elastic`` dispatches here
    when ``HOROVOD_KV_DIR`` + ``HOROVOD_DRIVER_SUPERVISE`` are set).

    With ``HOROVOD_KV_REPLICAS >= 2`` the supervisor also owns the KV
    replica fleet: N ``replica_kv`` subprocesses on pre-allocated ports,
    respawned individually when they die. The driver (and through it the
    workers) get the endpoint list via ``HOROVOD_KV_REPLICA_ENDPOINTS``
    and attach through failover clients — a SIGKILLed KV leader costs
    one election, not the control plane."""
    from horovod_tpu.runner.launch import _engine_env, free_port
    kv_dir = env_str("HOROVOD_KV_DIR")
    os.makedirs(kv_dir, exist_ok=True)
    replicas = env_int("HOROVOD_KV_REPLICAS")
    endpoints: Optional[List[str]] = None
    if replicas >= 2:
        ports = [free_port() for _ in range(replicas)]
        endpoints = [f"127.0.0.1:{p}" for p in ports]
        os.environ["HOROVOD_KV_REPLICA_ENDPOINTS"] = ",".join(endpoints)
        kv_port = ports[0]  # workers' seed endpoint; failover covers the rest
    else:
        # every driver incarnation must rebind the SAME KV port — the
        # workers' HOROVOD_RENDEZVOUS_PORT is fixed at spawn time
        kv_port = free_port()
    payload = {
        "min_np": args.min_np or args.num_proc,
        "max_np": args.max_np or args.num_proc or args.min_np,
        "host_discovery_script": args.host_discovery_script,
        "command": list(args.command),
        "extra_env": _engine_env(args),
        "reset_limit": args.reset_limit,
        "verbose": args.verbose,
        "start_timeout": args.start_timeout,
        "kv_port": kv_port,
    }
    args_path = os.path.join(kv_dir, _ARGS_FILE)
    with open(args_path, "w") as f:
        json.dump(payload, f)
    return _supervise([sys.executable, "-m",
                       "horovod_tpu.runner.elastic.supervisor",
                       "--driver", args_path], kv_dir,
                      replica_endpoints=endpoints)


class _ReplicaFleet:
    """The supervisor's KV replica subprocesses: spawn all, respawn any
    that die (each replays its own WAL and rejoins as a follower —
    rejoin resync repairs whatever suffix it lost or never committed)."""

    def __init__(self, endpoints: List[str], kv_dir: str):
        from horovod_tpu.runner.replica_kv import spawn_replica
        self._spawn = spawn_replica
        self.endpoints = endpoints
        self.kv_dir = kv_dir
        self.procs: dict = {}
        for i in range(len(endpoints)):
            self.procs[i] = self._spawn(i, endpoints, kv_dir)

    def reap_and_respawn(self):
        for i, p in list(self.procs.items()):
            rc = p.poll()
            if rc is not None:
                _logger.warning(
                    "kv replica %d died (exit %s); respawning: %s", i, rc,
                    json.dumps({"event": "kv_replica_respawn",
                                "replica": i, "exit_code": rc}))
                journal.emit("supervisor", "kv_replica_respawn",
                             replica=i, exit_code=rc)
                self.procs[i] = self._spawn(i, self.endpoints, self.kv_dir)

    def stop(self):
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 5.0
        for p in self.procs.values():
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()


def _supervise(cmd: List[str], kv_dir: str,
               replica_endpoints: Optional[List[str]] = None) -> int:
    limit = env_int("HOROVOD_DRIVER_RESTART_LIMIT")
    backoff = env_float("HOROVOD_DRIVER_RESTART_BACKOFF_SECONDS")
    restarts = 0
    stopping = {"sig": None}
    proc: Optional[subprocess.Popen] = None
    fleet = _ReplicaFleet(replica_endpoints, kv_dir) \
        if replica_endpoints else None

    def forward(sig, _frame):
        stopping["sig"] = sig
        if proc is not None and proc.poll() is None:
            proc.send_signal(sig)

    prev = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev[sig] = signal.signal(sig, forward)
        except ValueError:  # not the main thread (programmatic callers)
            pass
    try:
        while True:
            try:
                os.remove(_done_path(kv_dir))
            except OSError:
                pass
            from horovod_tpu.runner.replica_kv import die_with_parent
            # stdout/stderr inherited; PDEATHSIG so a SIGKILLed
            # supervisor can't leave an orphaned driver holding the
            # launcher's pipes open
            proc = subprocess.Popen(cmd, preexec_fn=die_with_parent)
            while True:
                try:
                    rc = proc.wait(timeout=1.0 if fleet else None)
                    break
                except subprocess.TimeoutExpired:
                    fleet.reap_and_respawn()
            done_rc = _read_done(kv_dir, proc.pid)
            if done_rc is not None:
                return done_rc
            if stopping["sig"] is not None:
                _logger.info("supervisor stopping on signal %s",
                             stopping["sig"])
                return 128 + int(stopping["sig"])
            restarts += 1
            event = {"event": "driver_crash", "exit_code": rc,
                     "restart": restarts, "limit": limit}
            _logger.warning("driver crashed: %s", json.dumps(event))
            journal.emit("supervisor", "driver_crash", exit_code=rc,
                         restart=restarts, limit=limit)
            sys.stderr.write(f"[supervisor] driver crashed (exit {rc}); "
                             f"respawn {restarts}/{limit}\n")
            sys.stderr.flush()
            if limit and restarts > limit:
                _logger.error("driver restart limit exhausted")
                journal.emit("supervisor", "restart_limit_exhausted",
                             exit_code=rc, restarts=restarts, limit=limit)
                return rc if rc else 1
            if backoff > 0:
                time.sleep(backoff)
    finally:
        if fleet is not None:
            fleet.stop()
        for sig, handler in prev.items():
            try:
                signal.signal(sig, handler)
            except ValueError:
                pass


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) == 2 and argv[0] == "--driver":
        return driver_main(argv[1])
    sys.stderr.write(
        "usage: python -m horovod_tpu.runner.elastic.supervisor "
        "--driver <driver_args.json>\n(the launcher invokes this; use "
        "hvdrun-tpu with HOROVOD_KV_DIR set instead)\n")
    return 2


if __name__ == "__main__":
    sys.exit(main())
