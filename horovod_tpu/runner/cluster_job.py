"""Executor-backed job orchestration core.

Reference analog: the pieces horovod/spark/runner.py:195-302 and
horovod/ray/runner.py:45-235 share — allocate the coordination endpoints on
the driver, hand every remote task the env contract, run the user function
on all tasks simultaneously, collect per-rank results.

The cluster schedulers themselves (Spark barrier stage, Ray actors) only
provide "run this closure on N tasks at once"; everything framework-
specific lives here so the spark/ray layers stay thin adapters and the
orchestration is testable with a local-process backend.
"""

from __future__ import annotations

import os
import socket
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

from horovod_tpu.runner.launch import free_ports, launcher_addr


def default_driver_addr() -> str:
    """Address remote tasks can use to reach a KV server bound on this
    (driver) host: the default-route interface's IP via the UDP-connect
    trick (no traffic sent); on air-gapped boxes with no default route,
    the hostname's resolved address; loopback as the last resort.
    Reference analog: the driver-service NIC probe picking a routable
    interface (runner/driver/driver_service.py:162-258)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 9))
        return s.getsockname()[0]
    except OSError:
        try:
            ip = socket.gethostbyname(socket.gethostname())
            if not ip.startswith("127."):
                return ip
        except OSError:
            pass
        return "127.0.0.1"
    finally:
        s.close()


def _self_addr_toward(peer_addr: str) -> str:
    """This host's address as seen on the route toward ``peer_addr``."""
    if peer_addr in ("127.0.0.1", "localhost", "::1"):
        return "127.0.0.1"
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((peer_addr, 9))
        return s.getsockname()[0]
    except OSError:
        return socket.getfqdn()
    finally:
        s.close()


class ClusterJobSpec:
    """Endpoints + per-rank env for one executor-backed job.

    Two endpoint modes:
    - ``rendezvous=(kv_addr, kv_port)``: dynamic — the rank-0 *task*
      allocates the controller/data ports on its own host at startup and
      publishes them (plus its routable address) through the driver's KV;
      other tasks poll. This avoids the driver-side free_port() TOCTOU
      (the driver may not even share a host with rank 0 under Spark/Ray)
      and needs no placement knowledge up front.
    - explicit ``controller_addr``: static — the driver allocates ports and
      bakes them into the env (single-host or caller-managed placement).
    """

    def __init__(self, num_proc: int,
                 controller_addr: Optional[str] = None,
                 extra_env: Optional[Dict[str, str]] = None,
                 rendezvous: Optional[Tuple[str, int]] = None):
        if num_proc < 1:
            raise ValueError(f"num_proc must be >= 1, got {num_proc}")
        self.num_proc = num_proc
        self.rendezvous = rendezvous
        self.job_id = uuid.uuid4().hex[:12]
        if rendezvous is not None and controller_addr is None:
            self.controller_addr = None
            self.controller_port = None
            self.data_port = None
        else:
            # Rank 0's engine binds the controller port on ITS host.
            # 127.0.0.1 is only correct when every task shares the driver's
            # host — warn rather than let remote workers spin on loopback.
            if controller_addr is None and num_proc > 1:
                import warnings
                warnings.warn(
                    "ClusterJobSpec without controller_addr or rendezvous "
                    "assumes all tasks run on the driver's host "
                    "(127.0.0.1); pass rendezvous=(kv_addr, kv_port) for "
                    "multi-node schedulers")
            self.controller_addr = controller_addr or launcher_addr([])
            self.controller_port, self.data_port = free_ports(2)
        self.extra_env = dict(extra_env or {})

    def worker_env(self, rank: int, local_rank: Optional[int] = None,
                   local_size: Optional[int] = None) -> Dict[str, str]:
        """Env for one task. Without explicit placement info the spec's
        single-host assumption applies (local == global); schedulers that
        know node placement (reference RayExecutor groups workers by node
        IP) should pass real local_rank/local_size."""
        if local_rank is None:
            local_rank = rank
        if local_size is None:
            local_size = self.num_proc
        env = dict(self.extra_env)
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(self.num_proc),
            "HOROVOD_LOCAL_RANK": str(local_rank),
            "HOROVOD_LOCAL_SIZE": str(local_size),
        })
        if self.controller_addr is not None:
            env.update({
                "HOROVOD_CONTROLLER_ADDR": self.controller_addr,
                "HOROVOD_CONTROLLER_PORT": str(self.controller_port),
                "HOROVOD_CONTROLLER_DATA_PORT": str(self.data_port),
            })
        if self.rendezvous is not None:
            env.update({
                "HOROVOD_RENDEZVOUS_ADDR": self.rendezvous[0],
                "HOROVOD_RENDEZVOUS_PORT": str(self.rendezvous[1]),
                "HOROVOD_CLUSTER_JOB": self.job_id,
            })
        # Deliberately no JAX_PLATFORMS default: on a TPU pod the workers
        # must auto-detect their accelerator; only an explicit driver
        # setting (or extra_env) is forwarded.
        if "JAX_PLATFORMS" in os.environ:
            env.setdefault("JAX_PLATFORMS", os.environ["JAX_PLATFORMS"])
        return env


def _negotiate_controller(env: Dict[str, str]) -> Dict[str, str]:
    """Task-side endpoint negotiation (dynamic mode): rank 0 allocates the
    controller/data ports on its own host — where its engine will bind
    moments later — and publishes them; everyone else polls. Returns the
    controller env entries."""
    from horovod_tpu.runner.http_kv import (KVClient,
                                            replica_endpoints_from_env)
    kv_addr = env["HOROVOD_RENDEZVOUS_ADDR"]
    client = KVClient(kv_addr, int(env["HOROVOD_RENDEZVOUS_PORT"]),
                      endpoints=replica_endpoints_from_env())
    # the round scopes the key per execution: long-lived actor pools
    # (RayExecutor) negotiate afresh on every run(), and ranks >0 must not
    # read a previous run's — now closed — endpoint
    rnd = env.get("HOROVOD_CLUSTER_ROUND", "0")
    from horovod_tpu.common import kv_keys
    key = kv_keys.cluster_controller(env["HOROVOD_CLUSTER_JOB"], rnd)
    if int(env["HOROVOD_RANK"]) == 0:
        port, data_port = free_ports(2)
        info = {"addr": _self_addr_toward(kv_addr), "port": port,
                "data_port": data_port}
        client.put_json(key, info)
    else:
        info = client.get_json(key, timeout=120.0)
        if info is None:
            raise RuntimeError(
                "rank 0 never published the controller endpoint "
                f"(KV {kv_addr}, job {env['HOROVOD_CLUSTER_JOB']})")
    return {
        "HOROVOD_CONTROLLER_ADDR": str(info["addr"]),
        "HOROVOD_CONTROLLER_PORT": str(info["port"]),
        "HOROVOD_CONTROLLER_DATA_PORT": str(info["data_port"]),
    }


def task_body(spec_env: Dict[str, str], fn: Callable, args: tuple,
              kwargs: dict) -> Any:
    """Runs inside the remote task: apply the env contract, execute, and
    return the result (the scheduler ships it back)."""
    spec_env = dict(spec_env)
    if ("HOROVOD_CONTROLLER_PORT" not in spec_env and
            "HOROVOD_CLUSTER_JOB" in spec_env):
        spec_env.update(_negotiate_controller(spec_env))
    os.environ.update(spec_env)
    # executors recycle processes: a previous job's context must not leak
    from horovod_tpu.common import basics
    basics.shutdown()
    return fn(*args, **kwargs)


def run_local_processes(spec: ClusterJobSpec, fn: Callable, args: tuple,
                        kwargs: dict, timeout: float = 300.0) -> List[Any]:
    """Local-process backend: the test double for a cluster scheduler, and
    a working fallback when neither Spark nor Ray is around. Semantics
    match the real backends: N simultaneous tasks, env contract applied,
    per-rank results in rank order."""
    import cloudpickle
    import subprocess
    import sys
    import tempfile

    with tempfile.TemporaryDirectory(prefix="hvdtpu_cluster_") as td:
        payload = os.path.join(td, "task.pkl")
        with open(payload, "wb") as f:
            cloudpickle.dump((fn, args, kwargs), f)
        script = os.path.join(td, "task.py")
        with open(script, "w") as f:
            f.write(
                "import sys, os, cloudpickle\n"
                f"sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))!r})\n"  # noqa: E501
                "from horovod_tpu.runner import cluster_job\n"
                f"fn, args, kwargs = cloudpickle.load(open({payload!r}, 'rb'))\n"  # noqa: E501
                "rank = int(sys.argv[1])\n"
                # route through task_body so dynamic-endpoint negotiation
                # runs exactly as it would under a real scheduler
                "result = cluster_job.task_body(dict(os.environ), fn, args, kwargs)\n"  # noqa: E501
                f"cloudpickle.dump(result, open(os.path.join({td!r}, f'r{{rank}}.pkl'), 'wb'))\n")  # noqa: E501
        procs = []
        try:
            for r in range(spec.num_proc):
                env = dict(os.environ)
                env.update(spec.worker_env(r))
                env.pop("PALLAS_AXON_POOL_IPS", None)
                procs.append(subprocess.Popen(
                    [sys.executable, script, str(r)], env=env,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
            import time
            deadline = time.monotonic() + timeout
            outs = []
            for p in procs:
                left = max(1.0, deadline - time.monotonic())
                outs.append(p.communicate(timeout=left)[0].decode())
        finally:
            # a stuck or failed rank must not leave peers blocked in
            # rendezvous holding the ports
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for r, (p, out) in enumerate(zip(procs, outs)):
            if p.returncode != 0:
                raise RuntimeError(f"task rank {r} failed:\n{out}")
        results = []
        for r in range(spec.num_proc):
            with open(os.path.join(td, f"r{r}.pkl"), "rb") as f:
                results.append(cloudpickle.load(f))
        return results
