"""Per-worker Prometheus HTTP endpoint.

Follows the ``runner/http_kv.py`` stdlib-server pattern: a daemonized
ThreadingHTTPServer, port 0 for ephemeral binding in tests. Two routes:

- ``GET /metrics``       — Prometheus text format (scrape target);
- ``GET /metrics.json``  — the registry's JSON snapshot (what the elastic
  driver polls on its heartbeat for straggler detection — structured,
  so the driver doesn't re-parse the text format);
- ``GET /agg.json``      — the per-host aggregate (local_rank 0 only,
  when ``HOROVOD_METRICS_AGG`` is on): co-located ranks' snapshots
  merged by :mod:`horovod_tpu.metrics.aggregator`, the driver's
  preferred O(hosts) scrape target. 404 on ranks without an aggregator.

Off by default: nothing binds unless ``HOROVOD_METRICS_PORT`` is set (see
``start_exporter_from_env``). Multiple workers per host offset the base
port by ``HOROVOD_LOCAL_RANK`` so one env value serves the whole host.
"""

from __future__ import annotations

import json
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from horovod_tpu.common.env_registry import (env_int, env_is_set, env_raw,
                                             env_str)
from horovod_tpu.metrics import prom
from horovod_tpu.metrics.registry import MetricsRegistry, get_registry


class MetricsExporter:
    """Threaded HTTP exporter over one registry."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 port: int = 0, addr: str = "0.0.0.0",
                 labels: Optional[Dict[str, str]] = None,
                 aggregator=None):
        self.registry = registry if registry is not None else get_registry()
        self.labels = dict(labels or {})
        self.aggregator = aggregator
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # silence
                pass

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path in ("/metrics", "/"):
                    body = prom.render(exporter.registry.collect(),
                                       exporter.labels).encode()
                    ctype = prom.CONTENT_TYPE
                elif path == "/metrics.json":
                    snap = exporter.registry.snapshot()
                    snap["labels"] = exporter.labels
                    body = json.dumps(snap).encode()
                    ctype = "application/json"
                elif path == "/agg.json" and exporter.aggregator is not None:
                    payload = exporter.aggregator.payload()
                    if payload is None:
                        # no aggregation pass has completed yet: 503 so
                        # the driver falls back to direct scrape instead
                        # of consuming an empty window as "no ranks"
                        self.send_response(503)
                        self.end_headers()
                        return
                    body = json.dumps(payload).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((addr, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsExporter":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self.aggregator is not None:
            self.aggregator.stop()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def start_exporter_from_env(registry: Optional[MetricsRegistry] = None,
                            rank: Optional[int] = None,
                            engine=None) -> Optional[MetricsExporter]:
    """Boot the per-worker endpoint when ``HOROVOD_METRICS_PORT`` is set
    (off by default). Called by ``hvd.init()``.

    - actual port = base + ``HOROVOD_LOCAL_RANK`` (base > 0), or ephemeral
      when the base itself is 0 (tests);
    - constant labels: ``rank`` and ``job`` (``HOROVOD_JOB_NAME``);
    - when an engine session is given, its ``hvd_engine_*`` collector is
      (re-)registered under the fixed name "engine" so elastic re-inits
      replace rather than stack collectors;
    - in an elastic job the endpoint address is published to the rendezvous
      KV under ``metrics_addr/<host>/<local_rank>`` for the driver's
      heartbeat scrape.

    Failure to bind logs a warning and returns None: observability must
    never take down training.
    """
    if not env_is_set("HOROVOD_METRICS_PORT"):
        return None
    from horovod_tpu.common.hvd_logging import get_logger
    log = get_logger("metrics")
    try:
        base = env_int("HOROVOD_METRICS_PORT")
        local_rank = env_int("HOROVOD_LOCAL_RANK")
        rank_label = rank if rank is not None else env_int("HOROVOD_RANK")
    except ValueError as e:
        # a malformed telemetry env var must not take down training
        log.warning("metrics exporter disabled, malformed env value "
                    "(HOROVOD_METRICS_PORT=%r): %s",
                    env_raw("HOROVOD_METRICS_PORT"), e)
        return None
    port = base + local_rank if base > 0 else 0
    reg = registry if registry is not None else get_registry()
    if engine is not None:
        from horovod_tpu.metrics.registry import engine_collector
        reg.register_collector(engine_collector(engine), name="engine")
    labels = {"rank": str(rank_label), "job": env_str("HOROVOD_JOB_NAME")}
    try:
        exporter = MetricsExporter(reg, port=port, labels=labels).start()
    except OSError as e:
        log.warning("metrics exporter could not bind port %s: %s", port, e)
        return None
    log.info("metrics endpoint on :%d/metrics", exporter.port)
    _publish_endpoint(exporter, log)
    if local_rank == 0:
        _start_host_aggregator(exporter, base, log)
    return exporter


def _publish_endpoint(exporter: MetricsExporter, log):
    """Elastic jobs: tell the driver where to scrape this worker."""
    try:
        addr = env_str("HOROVOD_RENDEZVOUS_ADDR")
        kv_port = env_int("HOROVOD_RENDEZVOUS_PORT")
        if not addr or not kv_port:
            return
        from horovod_tpu.runner.http_kv import (KVClient,
                                                replica_endpoints_from_env)
        host = env_str("HOROVOD_HOSTNAME", socket.gethostname())
        local_rank = str(env_int("HOROVOD_LOCAL_RANK"))
        scrape_addr = "127.0.0.1" if host == "localhost" else host
        from horovod_tpu.common import kv_keys
        KVClient(addr, kv_port,
                 endpoints=replica_endpoints_from_env()).put_json(
            kv_keys.metrics_addr(host, local_rank),
            {"addr": scrape_addr, "port": exporter.port,
             "rank": env_int("HOROVOD_RANK")},
            timeout=5.0)
    except Exception as e:  # noqa: BLE001 — best-effort publication
        log.warning("could not publish metrics endpoint: %s", e)


def _start_host_aggregator(exporter: MetricsExporter, base_port: int, log):
    """local_rank 0 hosts the per-host aggregation tier: a background
    scrape of every co-located rank's /metrics.json, served as /agg.json
    on this exporter and announced under ``agg_addr/<host>`` so the
    driver heartbeat scales O(hosts). Best-effort throughout — telemetry
    aggregation must never take down training."""
    from horovod_tpu.common.env_registry import env_bool
    if not env_bool("HOROVOD_METRICS_AGG"):
        return
    try:
        from horovod_tpu.common import kv_keys
        from horovod_tpu.metrics.aggregator import HostAggregator
        host = env_str("HOROVOD_HOSTNAME", socket.gethostname())
        local_size = max(1, env_int("HOROVOD_LOCAL_SIZE", 1))
        kv_addr = env_str("HOROVOD_RENDEZVOUS_ADDR")
        kv_port = env_int("HOROVOD_RENDEZVOUS_PORT")

        def discover():
            # KV-published endpoints first (elastic jobs; survives
            # ephemeral ports), base-port arithmetic otherwise
            targets = []
            if kv_addr and kv_port:
                from horovod_tpu.runner.http_kv import (
                    KVClient, replica_endpoints_from_env)
                client = KVClient(kv_addr, kv_port,
                                  endpoints=replica_endpoints_from_env())
                for lr in range(local_size):
                    try:
                        info = client.get_json(
                            kv_keys.metrics_addr(host, lr), timeout=1.0)
                    except Exception:  # noqa: BLE001 — KV blip
                        info = None
                    if isinstance(info, dict) and info.get("port"):
                        targets.append({"rank": info.get("rank", lr),
                                        "local_rank": lr,
                                        "addr": "127.0.0.1",
                                        "port": info["port"],
                                        "host": host})
                if targets:
                    return targets
            if base_port > 0:
                return [{"rank": lr, "local_rank": lr,
                         "addr": "127.0.0.1", "port": base_port + lr,
                         "host": host} for lr in range(local_size)]
            return [{"rank": env_int("HOROVOD_RANK"), "local_rank": 0,
                     "addr": "127.0.0.1", "port": exporter.port,
                     "host": host}]

        exporter.aggregator = HostAggregator(discover, host=host).start()
        log.info("per-host aggregator serving /agg.json on :%d",
                 exporter.port)
        if kv_addr and kv_port:
            from horovod_tpu.runner.http_kv import (
                KVClient, replica_endpoints_from_env)
            scrape_addr = "127.0.0.1" if host == "localhost" else host
            KVClient(kv_addr, kv_port,
                     endpoints=replica_endpoints_from_env()).put_json(
                kv_keys.agg_addr(host),
                {"addr": scrape_addr, "port": exporter.port, "host": host,
                 "local_size": local_size},
                timeout=5.0)
    except Exception as e:  # noqa: BLE001 — aggregation is optional
        log.warning("could not start host aggregator: %s", e)
