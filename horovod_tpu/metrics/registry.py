"""Lock-cheap process-local metrics registry.

The monitoring half of the observability stack (the profiler subsystem is
the post-hoc half, docs/DESIGN.md): counters, gauges and fixed-bucket
histograms fed from the Python hot paths (eager executor phases, frontend
step timers, traced in-jit collectives), plus pluggable *collectors* that
pull external sources at scrape time — chiefly the native engine's
``Session.metrics()`` JSON snapshot.

Design constraints:
- recording must be cheap enough to sit on the eager hot path: one
  ``threading.Lock`` acquire + an int add (~100ns) — no string formatting,
  no allocation on the hot path after the first call;
- metric identity is (name, sorted labels), Prometheus-style, so the
  exporter can render families directly;
- no third-party deps (the container bakes nothing in for this).
"""

from __future__ import annotations

import bisect
import threading
from typing import Callable, Dict, Iterable, List, NamedTuple, Optional, \
    Sequence, Tuple

# Default buckets, in seconds, spanning eager-collective latencies (100us)
# through slow multi-second steps.
DEFAULT_TIME_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
# Size-ish buckets (bytes, tensor counts).
DEFAULT_SIZE_BUCKETS = (
    1, 2, 4, 8, 16, 32, 64, 128, 1024, 16384, 262144, 1 << 20, 16 << 20,
    64 << 20,
)


class HistogramValue(NamedTuple):
    """Snapshot of a histogram: per-bucket (NOT cumulative) counts;
    ``counts`` has len(bounds)+1 entries (last = overflow)."""
    bounds: Tuple[float, ...]
    counts: Tuple[int, ...]
    sum: float
    count: int


class Metric(NamedTuple):
    """One family ready for rendering. ``samples`` maps a labels tuple
    (sorted (k, v) pairs) to a float (counter/gauge) or HistogramValue."""
    name: str
    kind: str  # "counter" | "gauge" | "histogram"
    help: str
    samples: List[Tuple[Tuple[Tuple[str, str], ...], object]]


class Counter:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0):
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float):
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    __slots__ = ("_lock", "bounds", "_counts", "_sum", "_count")

    def __init__(self, buckets: Sequence[float] = DEFAULT_TIME_BUCKETS):
        self._lock = threading.Lock()
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float):
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> HistogramValue:
        with self._lock:
            return HistogramValue(self.bounds, tuple(self._counts),
                                  self._sum, self._count)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Family:
    def __init__(self, kind: str, help: str):
        self.kind = kind
        self.help = help
        self.children: Dict[tuple, object] = {}


class MetricsRegistry:
    """Create-or-get instruments by (name, labels); collect families."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        self._collectors: Dict[str, Callable[[], Iterable[Metric]]] = {}

    def _get(self, name: str, kind: str, help: str, labels: dict, factory):
        key = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(kind, help)
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name} already registered as {fam.kind}")
            child = fam.children.get(key)
            if child is None:
                child = fam.children[key] = factory()
            return child

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get(name, "counter", help, labels, Counter)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get(name, "gauge", help, labels, Gauge)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
                  **labels) -> Histogram:
        return self._get(name, "histogram", help, labels,
                         lambda: Histogram(buckets))

    def register_collector(self, fn: Callable[[], Iterable[Metric]],
                           name: str = ""):
        """Attach a pull-source invoked at collect() time. Re-registering
        under the same ``name`` replaces the previous one (elastic re-init
        swaps the engine session without leaking a dead collector)."""
        with self._lock:
            self._collectors[name or f"_anon{len(self._collectors)}"] = fn

    def collect(self) -> List[Metric]:
        with self._lock:
            fams = {n: (f.kind, f.help, dict(f.children))
                    for n, f in self._families.items()}
            collectors = list(self._collectors.values())
        out: List[Metric] = []
        for name, (kind, help, children) in sorted(fams.items()):
            samples = []
            for key, child in sorted(children.items()):
                if kind == "histogram":
                    samples.append((key, child.snapshot()))
                else:
                    samples.append((key, child.value))
            out.append(Metric(name, kind, help, samples))
        for fn in collectors:
            try:
                out.extend(fn())
            except Exception:  # noqa: BLE001 — a dead source must not
                pass           # poison the scrape of everything else
        return out

    def snapshot(self) -> dict:
        """JSON-able view (the /metrics.json endpoint the elastic driver
        scrapes)."""
        metrics = []
        for m in self.collect():
            samples = []
            for key, v in m.samples:
                entry = {"labels": dict(key)}
                if isinstance(v, HistogramValue):
                    entry.update(bounds=list(v.bounds),
                                 counts=list(v.counts),
                                 sum=v.sum, count=v.count)
                else:
                    entry["value"] = v
                samples.append(entry)
            metrics.append({"name": m.name, "kind": m.kind,
                            "samples": samples})
        return {"metrics": metrics}


# ---------------------------------------------------------------------------
# engine bridge

# Engine histogram names carrying microsecond units, converted to seconds
# on export (Prometheus convention).
_US_HISTOGRAMS = {"cycle_us": "cycle_seconds", "exec_us": "exec_seconds"}

# HELP docstrings for the C++ MetricsStore families (the engine snapshot
# carries bare name/value pairs; the wire format wants a doc per family).
# Keys are the post-mapping names without the hvd_engine_ prefix / _total
# suffix. Anything the engine adds later falls back to a derived string in
# prom.render, so this map can lag a C++ release without breaking scrapes.
_ENGINE_HELP = {
    "enqueued": "tensors submitted to the engine queue",
    "allreduce_ops": "completed allreduce responses",
    "allgather_ops": "completed allgather responses",
    "broadcast_ops": "completed broadcast responses",
    "alltoall_ops": "completed alltoall responses",
    "barrier_ops": "completed barrier responses",
    "join_ops": "completed join responses",
    "error_responses": "responses delivered as errors",
    "allreduce_bytes": "payload bytes moved by allreduce",
    "allgather_bytes": "payload bytes moved by allgather",
    "broadcast_bytes": "payload bytes moved by broadcast",
    "alltoall_bytes": "payload bytes moved by alltoall",
    "cache_hits": "response-cache hits in the coordination loop",
    "cache_misses": "response-cache misses (full negotiation)",
    "cache_invalidations": "response-cache entries invalidated",
    "cache_evictions": "response-cache capacity evictions",
    "cycles": "coordination cycles run",
    "responses": "responses executed (fused batches count once)",
    "fused_responses": "responses that fused more than one tensor",
    "fused_tensors": "tensors carried by fused responses",
    "stall_warnings": "stall-inspector warning scans that fired",
    "stalled_tensors": "tensors named in stall warnings",
    "data_ring_ops": "data-plane ops routed over the ring",
    "data_star_ops": "data-plane ops routed over the star",
    "data_rd_ops": "data-plane ops routed over recursive doubling",
    "data_hier_ops": "data-plane ops routed over the hierarchical "
                     "two-level path",
    "data_interhost_bytes": "data-plane payload bytes sent to peers on "
                            "other hosts (locality map)",
    "data_intrahost_bytes": "data-plane payload bytes sent to same-host "
                            "peers (no locality map = all traffic)",
    "aborts": "fast-abort protocol activations",
    "connect_retries": "failed transport connect attempts",
    "crc_failures": "frames rejected by CRC32C",
    "faults_injected": "HOROVOD_FAULT_SPEC firings",
    "steps_marked": "frontend STEP_END marks (step attribution)",
    "low_latency_responses": "responses that rode the serving-mode "
                             "express lane (skipped fusion)",
    "queue_depth": "tensors staged but not yet negotiated",
    "cache_size": "response-cache entries resident",
    "fusion_batch_tensors": "tensors per fused response",
    "response_bytes": "payload bytes per response",
    "cycle_seconds": "coordination-cycle latency",
    "exec_seconds": "data-plane exec-callback latency",
}


def engine_collector(session) -> Callable[[], List[Metric]]:
    """Collector pulling ``session.metrics()`` (the C++ MetricsStore
    snapshot) into ``hvd_engine_*`` families at scrape time."""

    def collect() -> List[Metric]:
        try:
            snap = session.metrics()
        except Exception:  # noqa: BLE001 — session shut down mid-scrape
            return []
        if not snap:
            return []
        out: List[Metric] = []
        for k, v in sorted(snap.get("counters", {}).items()):
            out.append(Metric(f"hvd_engine_{k}_total", "counter",
                              _ENGINE_HELP.get(k, ""),
                              [((), float(v))]))
        for k, v in sorted(snap.get("gauges", {}).items()):
            out.append(Metric(f"hvd_engine_{k}", "gauge",
                              _ENGINE_HELP.get(k, ""),
                              [((), float(v))]))
        for k, h in sorted(snap.get("histograms", {}).items()):
            name, scale = k, 1.0
            if k in _US_HISTOGRAMS:
                name, scale = _US_HISTOGRAMS[k], 1e-6
            hv = HistogramValue(
                tuple(b * scale for b in h["bounds"]),
                tuple(h["counts"]), h["sum"] * scale, h["count"])
            out.append(Metric(f"hvd_engine_{name}", "histogram",
                              _ENGINE_HELP.get(name, ""),
                              [((), hv)]))
        return out

    return collect


# ---------------------------------------------------------------------------
# process-global default registry

_default_registry: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    global _default_registry
    with _default_lock:
        if _default_registry is None:
            _default_registry = MetricsRegistry()
        return _default_registry
