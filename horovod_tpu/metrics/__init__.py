"""Runtime metrics & cluster health telemetry.

The *monitoring* layer of the observability stack (the PR-2 profiler is
the *attribution* layer): live numeric telemetry from the running engine
and the Python hot paths, exported per worker in Prometheus text format
and aggregated by the elastic driver into straggler events.

Data flow (docs/DESIGN.md "Observability"):

    C++ MetricsStore ──hvdtpu_metrics_snapshot──▶ Session.metrics()
                                                     │ engine_collector
    Python hot paths ──registry instruments──▶ MetricsRegistry
                                                     │ prom.render
                         HOROVOD_METRICS_PORT ──▶ /metrics (per worker)
                                                     │ heartbeat scrape
                         elastic driver ──▶ step-time skew ──▶ straggler
                                                               events
"""

from __future__ import annotations

import time
from typing import Optional

from horovod_tpu.metrics.exporter import (  # noqa: F401
    MetricsExporter,
    start_exporter_from_env,
)
from horovod_tpu.metrics.registry import (  # noqa: F401
    DEFAULT_SIZE_BUCKETS,
    DEFAULT_TIME_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    HistogramValue,
    Metric,
    MetricsRegistry,
    engine_collector,
    get_registry,
)
from horovod_tpu.metrics.straggler import StragglerDetector  # noqa: F401

# Family names shared by every frontend step timer (keras callback, torch
# optimizer, the jax make_train_step wrapper) — the driver's straggler
# detection sums across frameworks, so they must agree.
STEP_SECONDS = "hvd_frontend_step_seconds"
STEPS_TOTAL = "hvd_frontend_steps_total"


def _get_attributor():
    """The step attributor behind the frontend timers, or None when
    disabled (HOROVOD_STEP_ATTRIBUTION=0). Late import: obs.attribution
    imports this package."""
    from horovod_tpu.obs.attribution import get_attributor
    return get_attributor()


class _TimedStep:
    """Wraps a (jitted) step callable: records wall time per invocation
    into the shared step-time histogram while forwarding everything else
    (``.lower``, AOT attributes) to the wrapped function.

    Also the frontend half of step-time attribution: each invocation is
    bracketed with engine STEP_BEGIN/STEP_END flight marks and fed to the
    rolling anomaly detector (horovod_tpu.obs.attribution) — one lock-free
    engine record each side plus a deque append, cheap enough for every
    step."""

    def __init__(self, fn, framework: str):
        self._fn = fn
        self._hist = get_registry().histogram(STEP_SECONDS,
                                              framework=framework)
        self._steps = get_registry().counter(STEPS_TOTAL,
                                             framework=framework)
        self._attr = None
        self._attr_resolved = False

    def __call__(self, *args, **kwargs):
        if not self._attr_resolved:
            # resolved on first step, not at wrap time: the attributor
            # needs the engine session, which init() creates later
            self._attr = _get_attributor()
            self._attr_resolved = True
        attr = self._attr
        sid = attr.next_step() if attr is not None else 0
        if attr is not None:
            attr.step_begin(sid)
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        self._hist.observe(dt)
        self._steps.inc()
        if attr is not None:
            attr.step_end(sid, dt)
        return out

    def __getattr__(self, item):
        # Never forward private/dunder probes: pickle and copy interrogate
        # __setstate__/__reduce__ before __init__ has run, and forwarding
        # would re-enter this method on the missing _fn (RecursionError).
        if item.startswith("_"):
            raise AttributeError(item)
        return getattr(object.__getattribute__(self, "_fn"), item)


def timed_step(fn, framework: str):
    """Instrument a train-step callable with the shared step timer.

    Note the async-dispatch caveat: under jax the recorded time is the
    dispatch+donation wall time of the call, which converges to the true
    step time in any steady-state loop (the next dispatch blocks on the
    previous step's donated buffers)."""
    return _TimedStep(fn, framework)


def record_step(framework: str, seconds: float,
                registry: Optional[MetricsRegistry] = None):
    """Record one frontend step duration (used by frontends that own their
    own timing, e.g. the torch optimizer and the keras callback).

    On the default registry the duration also feeds the step attributor's
    rolling anomaly detector; these frontends can't bracket the step with
    engine marks (they time after the fact), so they get anomaly events
    and gauges but no flight-ring windows."""
    reg = registry if registry is not None else get_registry()
    reg.histogram(STEP_SECONDS, framework=framework).observe(seconds)
    reg.counter(STEPS_TOTAL, framework=framework).inc()
    if registry is None:
        attr = _get_attributor()
        if attr is not None:
            attr.observe(seconds)


def snapshot_value(snapshot: dict, name: str, **labels) -> Optional[float]:
    """Scalar value of a counter/gauge family in a ``/metrics.json``
    snapshot (summed over samples matching ``labels`` — the families
    ``hvd-top`` and the driver read carry one sample each). None when the
    family is absent or no sample matches."""
    total, found = 0.0, False
    want = {str(k): str(v) for k, v in labels.items()}
    for m in snapshot.get("metrics", []):
        if m.get("name") != name:
            continue
        for s in m.get("samples", []):
            if "value" not in s:
                continue  # histogram family under a scalar lookup
            got = s.get("labels", {})
            if all(got.get(k) == v for k, v in want.items()):
                total += float(s["value"])
                found = True
    return total if found else None


def snapshot_histogram(snapshot: dict, name: str, **labels) -> Optional[dict]:
    """Merged histogram of a family in a ``/metrics.json`` snapshot:
    ``{"bounds": [...], "counts": [...], "sum": s, "count": n}`` with
    per-bucket (non-cumulative) counts, summed over samples matching
    ``labels``. None when absent/empty. Samples must share bucket bounds
    (true for every family one process exports)."""
    want = {str(k): str(v) for k, v in labels.items()}
    merged: Optional[dict] = None
    for m in snapshot.get("metrics", []):
        if m.get("name") != name:
            continue
        for s in m.get("samples", []):
            if "counts" not in s:
                continue
            got = s.get("labels", {})
            if not all(got.get(k) == v for k, v in want.items()):
                continue
            if merged is None:
                merged = {"bounds": list(s["bounds"]),
                          "counts": list(s["counts"]),
                          "sum": float(s.get("sum", 0.0)),
                          "count": int(s.get("count", 0))}
            elif list(s["bounds"]) == merged["bounds"]:
                merged["counts"] = [a + b for a, b in
                                    zip(merged["counts"], s["counts"])]
                merged["sum"] += float(s.get("sum", 0.0))
                merged["count"] += int(s.get("count", 0))
    return merged if merged and merged["count"] else None


def histogram_quantile(hist: dict, q: float) -> Optional[float]:
    """Estimate the ``q``-quantile (0..1) of a merged histogram
    (:func:`snapshot_histogram` shape) by linear interpolation inside the
    landing bucket — the standard Prometheus ``histogram_quantile``
    estimate. The overflow bucket clamps to its lower bound (no upper edge
    to interpolate toward). None for an empty histogram."""
    if not hist or not hist.get("count"):
        return None
    bounds, counts = hist["bounds"], hist["counts"]
    target = q * hist["count"]
    cum = 0.0
    for i, c in enumerate(counts):
        if cum + c >= target and c > 0:
            lo = bounds[i - 1] if i > 0 else 0.0
            if i >= len(bounds):
                return float(bounds[-1]) if bounds else None
            hi = bounds[i]
            frac = (target - cum) / c
            return lo + (hi - lo) * frac
        cum += c
    return float(bounds[-1]) if bounds else None


def step_stats(snapshot: dict) -> Optional[tuple]:
    """(count, sum_seconds) of the step-time histogram across frameworks
    from a ``/metrics.json`` snapshot — what the driver diffs per window.
    None when the worker has recorded no steps yet."""
    total_count, total_sum = 0, 0.0
    for m in snapshot.get("metrics", []):
        if m.get("name") != STEP_SECONDS:
            continue
        for s in m.get("samples", []):
            total_count += int(s.get("count", 0))
            total_sum += float(s.get("sum", 0.0))
    return (total_count, total_sum) if total_count else None


def bench_snapshot() -> dict:
    """Compact engine + frontend telemetry for the BENCH json
    (``engine_metrics`` field): the perf trajectory records cache hit
    rate and fusion efficiency alongside img/s, not instead of them."""
    out: dict = {"engine": None}
    reg_snap = get_registry().snapshot()
    st = step_stats(reg_snap)
    if st:
        out["frontend_steps"] = st[0]
        out["frontend_step_seconds_mean"] = round(st[1] / st[0], 6)
    try:
        from horovod_tpu.common import basics
        engine = basics._context().engine
    except Exception:  # noqa: BLE001
        engine = None
    if engine is not None:
        snap = engine.metrics()
        c = snap.get("counters", {})
        hits, misses = c.get("cache_hits", 0), c.get("cache_misses", 0)
        resp, tensors = c.get("responses", 0), c.get("fused_tensors", 0)
        out["engine"] = {
            "counters": c,
            "cache_hit_rate": round(hits / (hits + misses), 4)
            if hits + misses else None,
            "fusion_mean_tensors_per_response": round(tensors / resp, 3)
            if resp else None,
        }
    return out
