"""Per-host telemetry aggregation (the tiered scrape plane, ISSUE 18).

Every telemetry consumer — the elastic driver's heartbeat scrape,
straggler detection, the autoscaler's SLO loop, ``hvd-top`` — used to
read one ``/metrics.json`` per rank: O(N) HTTP round-trips per heartbeat,
the thing ROADMAP open item 3 names as breaking first at 1024 ranks.
This module is the middle tier that makes all of them O(hosts):

- :func:`merge_snapshots` — deterministic merge of co-located ranks'
  registry snapshots. **Counters are summed** (sorted-rank order, so two
  merges of the same inputs are byte-identical), **fixed-bucket
  histograms are bucket-wise added** (same bounds; differing bounds stay
  separate samples), and **gauges are kept as per-rank vectors** (each
  sample gains a ``rank`` label) — a summed queue depth is meaningful,
  a summed straggler score is not.
- :class:`HostAggregator` — hosted by local_rank 0's
  :class:`~horovod_tpu.metrics.exporter.MetricsExporter`: a background
  thread scrapes the co-located ranks' ``/metrics.json`` and publishes
  the merged view plus compact per-rank vectors (step stats, anomaly
  counters, serving SLO samples) as ``/agg.json``.
- :class:`TieredScrape` — the driver side of the tier, factored out of
  ``ElasticDriver._scrape_worker_metrics`` so tests and ``bench.py
  --telemetry-only`` drive the exact production consume path without a
  live driver. Per heartbeat each host is consumed through **exactly
  one** path: the aggregator when its ``/agg.json`` is fresh, the
  per-rank direct scrape otherwise (aggregator dead/stale) — never
  both, or counter deltas would double-count (``ScrapeSpec``'s
  ``no_double_count`` invariant, seeded mutant
  ``scrape_double_count_on_fallback``).

Staleness contract: ``/agg.json`` carries ``age_seconds`` computed on
the serving host (no cross-host clock skew); the driver falls back to
direct scrape past ``HOROVOD_AGG_STALE_SECONDS`` — the same bound
``hvd-top`` uses for its ``STALE DATA`` banner over aggregated rows.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

from horovod_tpu.common.env_registry import env_float
from horovod_tpu.metrics import snapshot_value, step_stats
from horovod_tpu.runner.http_kv import http_get_with_retry


def _label_key(labels: Dict[str, str]) -> Tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def merge_snapshots(snaps: List[Tuple[int, dict]]) -> dict:
    """Merge per-rank registry snapshots into one host-level snapshot of
    the same ``{"metrics": [{name, kind, samples}]}`` shape (so
    ``snapshot_value``/``snapshot_histogram``/``histogram_quantile`` read
    it unchanged).

    ``snaps`` is ``[(rank, snapshot), ...]``; ranks are processed in
    sorted order so the float accumulation is deterministic and two
    merges of the same inputs serialize byte-identically.
    """
    counters: Dict[Tuple, dict] = {}
    hists: Dict[Tuple, dict] = {}
    gauges: List[Tuple[Tuple, dict]] = []
    kinds: Dict[str, str] = {}
    order: List[str] = []
    for rank, snap in sorted(snaps, key=lambda rs: int(rs[0])):
        for m in snap.get("metrics", []):
            name, kind = m.get("name"), m.get("kind", "counter")
            if name not in kinds:
                kinds[name] = kind
                order.append(name)
            for s in m.get("samples", []):
                labels = dict(s.get("labels", {}))
                if kind == "gauge":
                    # per-rank vector: straggler-relevant gauges must not
                    # collapse (a summed score is meaningless); consumers
                    # select with rank=<r> or average over the vector
                    labels.setdefault("rank", str(rank))
                    gauges.append(((name, _label_key(labels)),
                                   {"labels": labels,
                                    "value": float(s.get("value", 0.0))}))
                elif "counts" in s:
                    key = (name, _label_key(labels),
                           tuple(s.get("bounds", [])))
                    cur = hists.get(key)
                    if cur is None:
                        hists[key] = {
                            "labels": labels,
                            "bounds": list(s.get("bounds", [])),
                            "counts": list(s.get("counts", [])),
                            "sum": float(s.get("sum", 0.0)),
                            "count": int(s.get("count", 0))}
                    else:
                        cur["counts"] = [a + b for a, b in
                                         zip(cur["counts"], s["counts"])]
                        cur["sum"] += float(s.get("sum", 0.0))
                        cur["count"] += int(s.get("count", 0))
                else:
                    key = (name, _label_key(labels))
                    cur = counters.get(key)
                    if cur is None:
                        counters[key] = {"labels": labels,
                                         "value": float(s.get("value", 0.0))}
                    else:
                        cur["value"] += float(s.get("value", 0.0))
    metrics = []
    for name in order:
        kind = kinds[name]
        if kind == "gauge":
            samples = [s for (n, _), s in gauges if n == name]
        elif any(k[0] == name for k in hists):
            samples = [s for k, s in hists.items() if k[0] == name]
        else:
            samples = [s for k, s in counters.items() if k[0] == name]
        metrics.append({"name": name, "kind": kind, "samples": samples})
    return {"metrics": metrics}


def counter_totals(snapshot: dict) -> Dict[str, float]:
    """{family name -> summed value} for every counter family in a
    snapshot — the quantity the BENCH telemetry block asserts
    byte-identical between the direct and tiered scrape paths."""
    out: Dict[str, float] = {}
    for m in snapshot.get("metrics", []):
        if m.get("kind") != "counter":
            continue
        total = 0.0
        for s in m.get("samples", []):
            if "value" in s:
                total += float(s["value"])
        out[m["name"]] = total
    return out


def _rank_vector(rank: int, local_rank, target: dict, snap: dict) -> dict:
    """The compact per-rank record the driver consumes from /agg.json:
    exactly what its straggler/anomaly/autoscaler paths read per rank."""
    from horovod_tpu.runner.elastic.autoscaler import worker_slo_from_snapshot
    vec = {
        "rank": int(rank),
        "local_rank": local_rank,
        "addr": target.get("addr"),
        "port": target.get("port"),
        "step": None,
        "anomalies": snapshot_value(snap, "hvd_step_anomaly_total"),
        "slo": None,
    }
    stats = step_stats(snap)
    if stats is not None:
        vec["step"] = [int(stats[0]), float(stats[1])]
    slo = worker_slo_from_snapshot(f"{target.get('host', '?')}/{local_rank}",
                                  snap)
    if slo is not None:
        vec["slo"] = slo._asdict()
    return vec


class HostAggregator:
    """Scrapes co-located ranks' ``/metrics.json`` and holds the merged
    ``/agg.json`` payload. Hosted by local_rank 0's exporter; pure HTTP
    client + JSON merge, no registry access of its own.

    ``targets``: list of ``{"rank", "local_rank", "addr", "port"}`` or a
    callable returning one (re-evaluated every refresh, so KV-discovered
    co-located ranks can come and go with elastic resizes).
    """

    def __init__(self, targets, host: str = "",
                 interval: Optional[float] = None,
                 timeout: float = 1.0):
        self._targets = targets
        self.host = host
        self.interval = interval if interval is not None else \
            env_float("HOROVOD_AGG_INTERVAL_SECONDS")
        self.timeout = timeout
        self._lock = threading.Lock()
        self._payload: Optional[dict] = None
        self._refreshed_mono: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.scrape_errors = 0

    # -- scrape + merge ------------------------------------------------------

    def _resolve_targets(self) -> List[dict]:
        t = self._targets() if callable(self._targets) else self._targets
        return list(t or [])

    def refresh(self) -> dict:
        """One aggregation pass: scrape every co-located rank, merge, and
        install the new payload. Unreachable ranks are simply absent from
        this window (the driver's fallback handles a whole-host outage;
        a single dead rank must not poison its host's aggregate)."""
        snaps: List[Tuple[int, dict]] = []
        ranks: Dict[str, dict] = {}
        errors = 0
        for t in self._resolve_targets():
            url = f"http://{t['addr']}:{t['port']}/metrics.json"
            try:
                snap = json.loads(http_get_with_retry(
                    url, timeout=self.timeout, attempts=1))
            except Exception:  # noqa: BLE001 — rank mid-restart
                errors += 1
                continue
            rank = int(t.get("rank", snap.get("labels", {}).get("rank", -1)))
            snaps.append((rank, snap))
            ranks[str(t.get("local_rank", rank))] = _rank_vector(
                rank, t.get("local_rank", rank), t, snap)
        payload = {
            "host": self.host,
            "ts": time.time(),
            "ranks": ranks,
            "merged": merge_snapshots(snaps),
            "scrape_errors": errors,
        }
        with self._lock:
            self._payload = payload
            self._refreshed_mono = time.monotonic()
            self.scrape_errors = errors
        return payload

    def payload(self) -> Optional[dict]:
        """The latest aggregate with its serve-time ``age_seconds``
        (computed on this host's monotonic clock — the staleness check
        never depends on cross-host clock sync). None before the first
        refresh completes."""
        with self._lock:
            if self._payload is None:
                return None
            out = dict(self._payload)
            out["age_seconds"] = round(
                time.monotonic() - self._refreshed_mono, 3)
        return out

    # -- background loop -----------------------------------------------------

    def start(self) -> "HostAggregator":
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="hvd-agg")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                self.refresh()
            except Exception:  # noqa: BLE001 — aggregation must never
                pass  # take down the worker hosting it
            self._stop.wait(self.interval)

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


# ===========================================================================
# Driver-side consumption (the tiered heartbeat)
# ===========================================================================

class ScrapeResult(NamedTuple):
    """One heartbeat's consumed telemetry, path bookkeeping included."""
    times: Dict[int, float]            # rank -> window mean step seconds
    targets: List[dict]                # per-rank metrics endpoints
    agg_targets: List[dict]            # live per-host aggregator endpoints
    anomalies: List[Tuple[Tuple[str, int], dict, float]]
    slos: List                         # WorkerSLO samples (autoscaler input)
    agg_hosts: List[str]               # hosts consumed via the aggregator
    fallback_hosts: List[str]          # hosts consumed via direct scrape


# Window-floor comparison slack (seconds). The payload's age is rounded
# to 1ms at serve time and both clock reads carry scheduling jitter, so
# re-deriving the SAME aggregation window's sample time across two
# heartbeats wobbles by a few ms — without slack, a driver beating
# faster than the aggregator refreshes would reject its own floor and
# fall back to the O(N) direct scrape every other beat. A real stale
# window is at least one refresh interval (1s default) behind; 50ms
# cleanly separates the two.
_WINDOW_SLACK_SECONDS = 0.05


class TieredScrape:
    """The driver's per-heartbeat scrape over the aggregator tier.

    For each host: read ``agg_addr/<host>`` from the KV, fetch
    ``/agg.json``, and consume the per-rank vectors when the payload is
    fresh; otherwise fall back to the per-rank direct scrape via
    ``metrics_addr/<host>/<slot>``. A host goes through exactly one path
    per heartbeat, and both paths diff against the SAME baseline maps
    (owned by the caller — the driver clears them on every generation
    change, exactly once, which is ``ScrapeSpec``'s
    ``baseline_reset_on_generation`` invariant)."""

    def __init__(self, kv_get_json: Callable[[str], Optional[dict]],
                 stale_seconds: Optional[float] = None,
                 timeout: float = 1.0, attempts: int = 2):
        self._kv_get = kv_get_json
        self.stale_seconds = stale_seconds if stale_seconds is not None \
            else env_float("HOROVOD_AGG_STALE_SECONDS")
        self.timeout = timeout
        self.attempts = attempts
        # per-host consume-window floor (driver monotonic clock): the
        # effective sample time of the newest telemetry already consumed
        # for the host. An agg payload whose scrape PREDATES this floor
        # is rejected even if age-fresh — consuming it would regress the
        # shared baselines below values a direct scrape already
        # installed, and the next window would re-count the difference
        # (double-counting via both paths across heartbeats; ScrapeSpec
        # mutant ``scrape_consume_stale_window``).
        self._window_floor: Dict[str, float] = {}

    def reset(self):
        """Forget consume-window floors (driver generation change — the
        caller clears the baseline maps at the same point)."""
        self._window_floor.clear()

    def _fetch_agg(self, host: str) -> Optional[dict]:
        from horovod_tpu.common import kv_keys
        info = self._kv_get(kv_keys.agg_addr(host))
        if not isinstance(info, dict) or not info.get("addr") \
                or not info.get("port"):
            return None
        try:
            url = f"http://{info['addr']}:{info['port']}/agg.json"
            payload = json.loads(http_get_with_retry(
                url, timeout=self.timeout, attempts=self.attempts,
                backoff=0.05))
        except Exception:  # noqa: BLE001 — aggregator dead: fall back
            return None
        if not isinstance(payload, dict) or "ranks" not in payload:
            return None
        age = payload.get("age_seconds")
        if age is None or float(age) > self.stale_seconds:
            return None  # stale aggregate: the fallback path owns this host
        # window-ordering guard: the payload's effective sample time on
        # OUR clock (age is a host-monotonic duration, so subtracting it
        # from our monotonic now involves no cross-host clock sync)
        sample_mono = time.monotonic() - float(age)
        if sample_mono < self._window_floor.get(host, float("-inf")) \
                - _WINDOW_SLACK_SECONDS:
            return None  # age-fresh but older than what we consumed
        payload["_addr"] = info["addr"]
        payload["_port"] = info["port"]
        payload["_sample_mono"] = sample_mono
        return payload

    def heartbeat(self, slots: List[Tuple[str, int]],
                  metrics_prev: Dict[Tuple[str, int], tuple],
                  anomaly_prev: Dict[Tuple[str, int], float],
                  want_slo: bool = False) -> ScrapeResult:
        """Consume one heartbeat window for ``slots`` (host, local_rank
        pairs), diffing step/anomaly counters into the caller-owned
        baseline maps."""
        from horovod_tpu.common import kv_keys
        times: Dict[int, float] = {}
        targets: List[dict] = []
        agg_targets: List[dict] = []
        anomalies: List[Tuple[Tuple[str, int], dict, float]] = []
        slos: List = []
        agg_hosts: List[str] = []
        fallback_hosts: List[str] = []

        by_host: Dict[str, List[int]] = {}
        for host, lr in slots:
            by_host.setdefault(host, []).append(lr)

        for host in sorted(by_host):
            payload = self._fetch_agg(host)
            if payload is not None:
                self._window_floor[host] = max(
                    self._window_floor.get(host, float("-inf")),
                    payload["_sample_mono"])
                agg_hosts.append(host)
                agg_targets.append({"host": host, "addr": payload["_addr"],
                                    "port": payload["_port"],
                                    "age_seconds": payload.get(
                                        "age_seconds")})
                ranks = payload.get("ranks", {})
                for lr in by_host[host]:
                    vec = ranks.get(str(lr))
                    if not isinstance(vec, dict):
                        continue  # rank missed this aggregation window
                    self._consume_rank(
                        host, lr, vec, metrics_prev, anomaly_prev,
                        times, targets, anomalies, slos, want_slo)
                continue
            # fallback: aggregator dead or stale — direct per-rank scrape,
            # never in the same heartbeat as an agg consume of this host
            fallback_hosts.append(host)
            self._window_floor[host] = time.monotonic()
            for lr in by_host[host]:
                info = self._kv_get(kv_keys.metrics_addr(host, lr))
                if not isinstance(info, dict) or not info.get("addr") \
                        or not info.get("port"):
                    continue
                try:
                    url = (f"http://{info['addr']}:{info['port']}"
                           f"/metrics.json")
                    snap = json.loads(http_get_with_retry(
                        url, timeout=self.timeout, attempts=self.attempts,
                        backoff=0.05))
                except Exception:  # noqa: BLE001 — worker mid-restart
                    continue
                vec = _rank_vector(int(info.get("rank", -1)), lr,
                                   {"addr": info["addr"],
                                    "port": info["port"], "host": host},
                                   snap)
                self._consume_rank(
                    host, lr, vec, metrics_prev, anomaly_prev,
                    times, targets, anomalies, slos, want_slo)
        return ScrapeResult(times, targets, agg_targets, anomalies, slos,
                            agg_hosts, fallback_hosts)

    @staticmethod
    def _consume_rank(host, lr, vec, metrics_prev, anomaly_prev,
                      times, targets, anomalies, slos, want_slo):
        """Diff one rank's vector against the shared baselines — the one
        consume path both tiers funnel through, so a rank can never be
        double-counted within a heartbeat and counter totals stay
        monotonic across an aggregator death + fallback (the baselines
        survive the path switch)."""
        key = (host, lr)
        if vec.get("addr") and vec.get("port"):
            targets.append({"addr": vec["addr"], "port": vec["port"],
                            "rank": vec.get("rank")})
        count = vec.get("anomalies")
        if count is not None:
            prev_count = anomaly_prev.get(key)
            anomaly_prev[key] = float(count)
            if prev_count is not None and count > prev_count:
                anomalies.append((key, {"rank": vec.get("rank")},
                                  float(count) - prev_count))
        if want_slo and isinstance(vec.get("slo"), dict):
            from horovod_tpu.runner.elastic.autoscaler import WorkerSLO
            try:
                slos.append(WorkerSLO(**vec["slo"]))
            except TypeError:
                pass  # vector from a different version: skip, don't crash
        step = vec.get("step")
        if not step:
            return
        stats = (int(step[0]), float(step[1]))
        prev = metrics_prev.get(key)
        metrics_prev[key] = stats
        if prev is not None and stats[0] > prev[0]:
            times[int(vec.get("rank", -1))] = \
                (stats[1] - prev[1]) / (stats[0] - prev[0])
