"""Prometheus text exposition format (version 0.0.4) rendering.

Kept dependency-free: the wire format is a handful of escaping rules and
the cumulative-``le`` histogram convention, not worth a client library.
Constant labels (``rank``, ``job``) are merged into every sample so a
cluster-level Prometheus can aggregate per-worker scrapes.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Tuple

from horovod_tpu.metrics.registry import HistogramValue, Metric

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n") \
        .replace('"', '\\"')


def _escape_help(v: str) -> str:
    # HELP docstrings escape only backslash and newline (no quotes)
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(pairs: Iterable[Tuple[str, str]]) -> str:
    items = [f'{k}="{_escape_label(v)}"' for k, v in pairs]
    return "{" + ",".join(items) + "}" if items else ""


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _merged(sample_labels, const_labels: Dict[str, str],
            extra: Dict[str, str] = None) -> List[Tuple[str, str]]:
    merged = dict(const_labels)
    merged.update(dict(sample_labels))
    if extra:
        merged.update(extra)
    return sorted(merged.items())


def render(metrics: Iterable[Metric],
           const_labels: Dict[str, str] = None) -> str:
    """Render families into the text format. Histogram buckets are emitted
    cumulatively with the ``le`` label plus the required ``+Inf`` bucket,
    ``_sum`` and ``_count`` series."""
    const_labels = const_labels or {}
    lines: List[str] = []
    for m in metrics:
        if not m.samples:
            continue
        # Every family gets both comment lines — real Prometheus scrapers
        # (and promtool check metrics) expect HELP before TYPE for each
        # metric, so a help-less registration still emits a derived one.
        help_text = m.help or \
            m.name.replace("_", " ") + f" ({m.kind}, no help registered)"
        lines.append(f"# HELP {m.name} {_escape_help(help_text)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for sample_labels, value in m.samples:
            if isinstance(value, HistogramValue):
                cum = 0
                for bound, count in zip(value.bounds, value.counts):
                    cum += count
                    labels = _merged(sample_labels, const_labels,
                                     {"le": _fmt_value(bound)})
                    lines.append(
                        f"{m.name}_bucket{_fmt_labels(labels)} {cum}")
                labels = _merged(sample_labels, const_labels,
                                 {"le": "+Inf"})
                lines.append(
                    f"{m.name}_bucket{_fmt_labels(labels)} {value.count}")
                base = _fmt_labels(_merged(sample_labels, const_labels))
                lines.append(f"{m.name}_sum{base} {_fmt_value(value.sum)}")
                lines.append(f"{m.name}_count{base} {value.count}")
            else:
                labels = _fmt_labels(_merged(sample_labels, const_labels))
                lines.append(f"{m.name}{labels} {_fmt_value(value)}")
    return "\n".join(lines) + "\n"


def parse_samples(text: str) -> Dict[str, Dict[Tuple[Tuple[str, str], ...],
                                               float]]:
    """Minimal parser for tests/diagnostics: {name: {labels_tuple: value}}.
    Handles the subset render() emits (no exemplars, no timestamps)."""
    out: Dict[str, Dict[tuple, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value_part = line.rpartition(" ")
        labels: List[Tuple[str, str]] = []
        if "{" in name_part:
            name, _, rest = name_part.partition("{")
            body = rest.rstrip("}")
            # label values render() emits never contain unescaped commas
            # inside quotes beyond these simple cases
            for item in _split_labels(body):
                k, _, v = item.partition("=")
                labels.append((k, v.strip('"').replace('\\"', '"')
                               .replace("\\n", "\n").replace("\\\\", "\\")))
        else:
            name = name_part
        value = float("inf") if value_part == "+Inf" else float(value_part)
        out.setdefault(name, {})[tuple(sorted(labels))] = value
    return out


def _split_labels(body: str) -> List[str]:
    items, cur, in_q, esc = [], [], False, False
    for ch in body:
        if esc:
            cur.append(ch)
            esc = False
        elif ch == "\\":
            cur.append(ch)
            esc = True
        elif ch == '"':
            cur.append(ch)
            in_q = not in_q
        elif ch == "," and not in_q:
            items.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        items.append("".join(cur))
    return items
