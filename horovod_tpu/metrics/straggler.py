"""Per-rank step-time skew analysis (straggler detection).

The elastic driver scrapes every worker's ``/metrics.json`` on its
discovery heartbeat, turns the ``hvd_frontend_step_seconds`` histogram
deltas into a mean step time per rank per window, and feeds the windows
here. A rank is flagged when its step time exceeds
``median + k * sigma`` of its *peers* (leave-one-out — with small worlds
the straggler itself would otherwise inflate the median and sigma it is
judged against) for ``windows`` consecutive heartbeats.

``sigma`` is floored at ``min_rel_skew * median`` so a perfectly uniform
fleet (sigma → 0) doesn't flag micro-jitter, and a rank is only re-flagged
after it recovers (one structured event per slow episode, not one per
heartbeat).

Pure logic, no I/O — unit-testable without processes; the driver owns the
scraping and the structured-event logging.
"""

from __future__ import annotations

import statistics
from typing import Dict, List


class StragglerDetector:
    def __init__(self, k: float = 3.0, windows: int = 3,
                 min_rel_skew: float = 0.05):
        self.k = float(k)
        self.windows = int(windows)
        self.min_rel_skew = float(min_rel_skew)
        self._streak: Dict[int, int] = {}
        self._flagged: set = set()

    def update(self, step_times: Dict[int, float]) -> List[dict]:
        """Feed one window of per-rank mean step times; returns the
        structured straggler events that fired on this window."""
        events: List[dict] = []
        # ranks that disappeared (scrape failure / rescale) lose their state
        for r in list(self._streak):
            if r not in step_times:
                self._streak.pop(r, None)
                self._flagged.discard(r)
        if len(step_times) < 2:
            return events
        for r, t in step_times.items():
            others = [v for o, v in step_times.items() if o != r]
            med = statistics.median(others)
            sigma = statistics.pstdev(others) if len(others) > 1 else 0.0
            sigma = max(sigma, self.min_rel_skew * med)
            threshold = med + self.k * sigma
            if med > 0 and t > threshold:
                self._streak[r] = self._streak.get(r, 0) + 1
            else:
                self._streak.pop(r, None)
                self._flagged.discard(r)
                continue
            if self._streak[r] >= self.windows and r not in self._flagged:
                self._flagged.add(r)
                events.append({
                    "event": "straggler",
                    "rank": r,
                    "step_time_sec": t,
                    "median_sec": med,
                    "sigma_sec": sigma,
                    "threshold_sec": threshold,
                    "consecutive_windows": self._streak[r],
                })
        return events

    @property
    def flagged(self) -> set:
        """Ranks currently in a flagged episode."""
        return set(self._flagged)
