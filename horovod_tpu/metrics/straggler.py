"""Per-rank step-time skew analysis (straggler detection).

The elastic driver scrapes every worker's ``/metrics.json`` on its
discovery heartbeat, turns the ``hvd_frontend_step_seconds`` histogram
deltas into a mean step time per rank per window, and feeds the windows
here. A rank is flagged when its step time exceeds
``median + k * sigma`` of its *peers* (leave-one-out — with small worlds
the straggler itself would otherwise inflate the median and sigma it is
judged against) for ``windows`` consecutive heartbeats.

``sigma`` is floored at ``min_rel_skew * median`` so a perfectly uniform
fleet (sigma → 0) doesn't flag micro-jitter, and a rank is only re-flagged
after it recovers (one structured event per slow episode, not one per
heartbeat).

Pure logic, no blocking I/O — unit-testable without processes; the driver
owns the scraping and the structured-event logging. When a ``registry``
is supplied, every window also exports per-rank gauges —
``hvd_straggler_score{rank=R}`` (peer-relative skew in sigmas,
``(t - median) / sigma``) and ``hvd_straggler_flagged{rank=R}`` — so
``/metrics`` serves the live scores, not just the logged/KV events.
"""

from __future__ import annotations

import statistics
from typing import Dict, List


class StragglerDetector:
    def __init__(self, k: float = 3.0, windows: int = 3,
                 min_rel_skew: float = 0.05, registry=None):
        self.k = float(k)
        self.windows = int(windows)
        self.min_rel_skew = float(min_rel_skew)
        self._registry = registry
        self._streak: Dict[int, int] = {}
        self._flagged: set = set()
        self.last_scores: Dict[int, float] = {}

    def _export(self, rank: int, score: float):
        self.last_scores[rank] = score
        if self._registry is None:
            return
        self._registry.gauge(
            "hvd_straggler_score",
            help="peer-relative step-time skew in sigmas, (t - median)/sigma",
            rank=str(rank)).set(score)
        self._registry.gauge(
            "hvd_straggler_flagged",
            help="1 while the rank is in a flagged straggler episode",
            rank=str(rank)).set(1.0 if rank in self._flagged else 0.0)

    def update(self, step_times: Dict[int, float]) -> List[dict]:
        """Feed one window of per-rank mean step times; returns the
        structured straggler events that fired on this window."""
        events: List[dict] = []
        # ranks that disappeared (scrape failure / rescale) lose their
        # state — including their exported gauges, or /metrics would keep
        # reporting a departed rank as a flagged straggler forever
        for r in list(self._streak):
            if r not in step_times:
                self._streak.pop(r, None)
                self._flagged.discard(r)
        for r in list(self.last_scores):
            if r not in step_times:
                self.last_scores.pop(r, None)
                if self._registry is not None:
                    self._registry.gauge("hvd_straggler_score",
                                         rank=str(r)).set(0.0)
                    self._registry.gauge("hvd_straggler_flagged",
                                         rank=str(r)).set(0.0)
        if len(step_times) < 2:
            return events
        for r, t in step_times.items():
            others = [v for o, v in step_times.items() if o != r]
            med = statistics.median(others)
            sigma = statistics.pstdev(others) if len(others) > 1 else 0.0
            sigma = max(sigma, self.min_rel_skew * med)
            threshold = med + self.k * sigma
            if med > 0 and t > threshold:
                self._streak[r] = self._streak.get(r, 0) + 1
            else:
                self._streak.pop(r, None)
                self._flagged.discard(r)
                self._export(r, (t - med) / sigma if sigma > 0 else 0.0)
                continue
            if self._streak[r] >= self.windows and r not in self._flagged:
                self._flagged.add(r)
                events.append({
                    "event": "straggler",
                    "rank": r,
                    "step_time_sec": t,
                    "median_sec": med,
                    "sigma_sec": sigma,
                    "threshold_sec": threshold,
                    "consecutive_windows": self._streak[r],
                })
            # exported after the flag update so the flagged gauge flips in
            # the same window as the event
            self._export(r, (t - med) / sigma if sigma > 0 else 0.0)
        return events

    def reset(self):
        """Drop all rolling state (streaks, flagged episodes, scores) and
        zero the exported per-rank gauges.

        The elastic driver calls this on every topology generation change:
        after a resize the rank→host mapping shifts, so pre-resize samples
        and streaks would be charged to whichever rank inherited the
        number — a healthy worker could be flagged on another machine's
        history."""
        for r in list(self.last_scores):
            if self._registry is not None:
                self._registry.gauge("hvd_straggler_score",
                                     rank=str(r)).set(0.0)
                self._registry.gauge("hvd_straggler_flagged",
                                     rank=str(r)).set(0.0)
        self._streak.clear()
        self._flagged.clear()
        self.last_scores.clear()

    @property
    def flagged(self) -> set:
        """Ranks currently in a flagged episode."""
        return set(self._flagged)
