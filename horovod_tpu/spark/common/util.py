"""Estimator data plane: DataFrame -> sharded Parquet -> worker arrays.

Reference analog: horovod/spark/common/util.py (prepare_data /
get_simple_meta_from_parquet / dataset metadata, :362-700). The reference
stages through Petastorm; here the data plane is pyarrow Parquet + numpy —
the form a TPU input pipeline wants (dense host arrays it can stack into
device batches) — and both pandas and pyspark DataFrames are accepted,
so the estimators work with or without a Spark session.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def _is_spark_df(df) -> bool:
    return hasattr(df, "toPandas") and hasattr(df, "rdd")


def _meta_path(data_path: str) -> str:
    return os.path.join(data_path, "_hvdtpu_metadata.json")


def _column_metadata(pdf) -> Dict[str, dict]:
    """Per-column dtype + per-row shape ([] scalar, [n] fixed list)."""
    meta = {}
    for col in pdf.columns:
        first = pdf[col].iloc[0] if len(pdf) else 0.0
        if isinstance(first, (list, tuple, np.ndarray)):
            arr = np.asarray(first)
            meta[col] = {"dtype": str(arr.dtype), "shape": list(arr.shape)}
        else:
            meta[col] = {"dtype": str(np.asarray(first).dtype), "shape": []}
    return meta


def _write_pandas_shards(pdf, path: str, num_shards: int):
    """Write a pandas frame as ``num_shards`` Parquet files (one per
    training process; round-robin rows so every shard is non-empty when
    rows >= shards)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    shutil.rmtree(path, ignore_errors=True)
    os.makedirs(path, exist_ok=True)
    for i in range(num_shards):
        shard = pdf.iloc[i::num_shards]
        table = pa.Table.from_pandas(shard.reset_index(drop=True),
                                     preserve_index=False)
        pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"))


def _row_count_and_size(path: str) -> Tuple[int, int]:
    import pyarrow.parquet as pq
    rows, bytes_ = 0, 0
    for f in sorted(os.listdir(path)):
        if not f.endswith(".parquet"):
            continue
        fp = os.path.join(path, f)
        rows += pq.ParquetFile(fp).metadata.num_rows
        bytes_ += os.path.getsize(fp)
    return rows, (bytes_ // max(rows, 1))


@contextlib.contextmanager
def prepare_data(num_processes: int, store, df,
                 label_columns: Sequence[str],
                 feature_columns: Sequence[str],
                 validation=None,
                 sample_weight_col: Optional[str] = None,
                 compress_sparse: bool = False,
                 partitions_per_process: Optional[int] = None,
                 verbose: int = 0):
    """Stage ``df`` into the store as train/val Parquet shards; yields the
    dataset index (reference: util.py prepare_data). ``validation`` is a
    float fraction, a boolean column name, or None.

    Unlike the reference there is no content-hash cache: each fit stages
    afresh (the Parquet write is the cheap part of a training run, and a
    stale-cache surprise is worse than a rewrite).
    """
    _ = (compress_sparse, partitions_per_process)
    idx = 0
    cols = list(dict.fromkeys(
        list(feature_columns) + list(label_columns) +
        ([sample_weight_col] if sample_weight_col else []) +
        ([validation] if isinstance(validation, str) else [])))
    if _is_spark_df(df):
        df = df.select(*cols).toPandas()
    else:
        missing = [c for c in cols if c not in df.columns]
        if missing:
            raise ValueError(f"columns {missing} not in DataFrame")
        df = df[cols]

    if validation is None:
        train_pdf, val_pdf = df, None
    elif isinstance(validation, float):
        if not 0.0 < validation < 1.0:
            raise ValueError(f"validation fraction must be in (0, 1), "
                             f"got {validation}")
        n_val = max(1, int(round(len(df) * validation)))
        rs = np.random.RandomState(0)
        perm = rs.permutation(len(df))
        val_pdf = df.iloc[perm[:n_val]]
        train_pdf = df.iloc[perm[n_val:]]
    elif isinstance(validation, str):
        mask = df[validation].astype(bool)
        val_pdf = df[mask].drop(columns=[validation])
        train_pdf = df[~mask].drop(columns=[validation])
    else:
        raise ValueError(f"validation must be None, float, or column name; "
                         f"got {type(validation)}")

    train_path = store.get_train_data_path(idx)
    _write_pandas_shards(train_pdf, train_path, num_processes)
    meta = {
        "columns": _column_metadata(train_pdf),
        "label_columns": list(label_columns),
        "feature_columns": list(feature_columns),
        "sample_weight_col": sample_weight_col,
    }
    with open(_meta_path(train_path), "w") as f:
        json.dump(meta, f)
    val_path = store.get_val_data_path(idx)
    if val_pdf is not None and len(val_pdf):
        _write_pandas_shards(val_pdf, val_path, num_processes)
    else:
        # a previous fit's staged validation shards must not leak into
        # this run (workers gate on the path's existence)
        shutil.rmtree(val_path, ignore_errors=True)
    if verbose:
        print(f"[horovod_tpu.spark] staged {len(train_pdf)} train / "
              f"{0 if val_pdf is None else len(val_pdf)} val rows "
              f"to {train_path}")
    yield idx


def get_dataset_properties(store, idx: int = 0):
    """(train_rows, val_rows, metadata, avg_row_size) of a staged dataset
    (reference: util.py get_dataset_properties)."""
    train_path = store.get_train_data_path(idx)
    train_rows, avg_row_size = _row_count_and_size(train_path)
    val_path = store.get_val_data_path(idx)
    val_rows = _row_count_and_size(val_path)[0] if store.exists(val_path) \
        else 0
    with open(_meta_path(train_path)) as f:
        metadata = json.load(f)
    return train_rows, val_rows, metadata, avg_row_size


def get_simple_meta_from_parquet(store, label_columns, feature_columns,
                                 sample_weight_col=None, idx: int = 0):
    """Metadata for an externally staged Parquet dataset at the store's
    train path (reference: util.py get_simple_meta_from_parquet). Writes
    the metadata sidecar if absent so fit_on_parquet works on data the
    estimator didn't stage itself."""
    import pyarrow.parquet as pq

    train_path = store.get_train_data_path(idx)
    if not os.path.exists(_meta_path(train_path)):
        files = [f for f in sorted(os.listdir(train_path))
                 if f.endswith(".parquet")]
        if not files:
            raise ValueError(f"no parquet files at {train_path}")
        pdf = pq.ParquetFile(
            os.path.join(train_path, files[0])).read().to_pandas()
        meta = {
            "columns": _column_metadata(pdf),
            "label_columns": list(label_columns),
            "feature_columns": list(feature_columns),
            "sample_weight_col": sample_weight_col,
        }
        with open(_meta_path(train_path), "w") as f:
            json.dump(meta, f)
    return get_dataset_properties(store, idx)


def read_shard(data_path: str, rank: int, size: int,
               columns: Optional[List[str]] = None):
    """This rank's rows of a staged dataset as a pandas DataFrame.

    Sharding is file-granular when the writer produced >= size files (the
    prepare_data layout); otherwise row-granular (rank strides rows) so
    externally staged datasets with few files still split correctly.
    """
    import pandas as pd
    import pyarrow.parquet as pq

    files = [os.path.join(data_path, f)
             for f in sorted(os.listdir(data_path))
             if f.endswith(".parquet")]
    if not files:
        raise ValueError(f"no parquet files at {data_path}")
    if len(files) >= size:
        mine = files[rank::size]
        parts = [pq.read_table(f, columns=columns).to_pandas()
                 for f in mine]
        return pd.concat(parts, ignore_index=True) if parts else \
            pq.read_table(files[0], columns=columns).to_pandas().iloc[:0]
    full = pd.concat([pq.read_table(f, columns=columns).to_pandas()
                      for f in files], ignore_index=True)
    return full.iloc[rank::size].reset_index(drop=True)


def assemble_features(pdf, feature_columns: Sequence[str]) -> np.ndarray:
    """Stack feature columns into one dense (rows, features) float32 array
    — scalars contribute one column, fixed-size list/array columns expand
    (the role of the reference's vector assembly in util.py:
    dense features ride a single MXU-friendly matrix)."""
    blocks = []
    for col in feature_columns:
        vals = pdf[col].to_numpy()
        if len(vals) and isinstance(vals[0], (list, tuple, np.ndarray)):
            block = np.stack([np.asarray(v, np.float32).ravel()
                              for v in vals])
        else:
            block = np.asarray(vals, np.float32).reshape(len(vals), 1)
        blocks.append(block)
    if not blocks:
        raise ValueError("no feature columns")
    return np.concatenate(blocks, axis=1).astype(np.float32)


def assemble_labels(pdf, label_columns: Sequence[str]) -> np.ndarray:
    """(rows, len(label_columns)) float32 label matrix; single-column
    labels stay 2D for a uniform loss interface."""
    cols = [np.asarray(pdf[c].to_numpy(), np.float32).reshape(len(pdf), -1)
            for c in label_columns]
    return np.concatenate(cols, axis=1)
