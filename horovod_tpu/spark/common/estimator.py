"""HorovodEstimator / HorovodModel base classes.

Reference analog: horovod/spark/common/estimator.py:25-133 — the
``fit(df) -> model transformer`` shape of the Spark estimator stack:
stage the DataFrame into the store as Parquet, run distributed training
on the backend's processes (each reading its shard), checkpoint rank 0's
result into the store, and wrap it in a Model whose ``transform`` adds
prediction columns.

Works on pandas DataFrames without pyspark; with a Spark session, input
and output are real Spark DataFrames.
"""

from __future__ import annotations

import uuid
from typing import Optional

import numpy as np

from horovod_tpu.spark.common import util
from horovod_tpu.spark.common.params import EstimatorParams, ModelParams


class HorovodEstimator(EstimatorParams):
    def fit(self, df, params: Optional[dict] = None):
        """Fit on a DataFrame (pandas or pyspark); returns the fitted
        HorovodModel transformer (reference: estimator.py:26-35)."""
        if params:
            return self.copy(params).fit(df)
        backend = self._get_or_create_backend()
        store = self._require_store()
        with util.prepare_data(
                backend.num_processes(), store, df,
                label_columns=self.getLabelCols(),
                feature_columns=self.getFeatureCols(),
                validation=self.getValidation(),
                sample_weight_col=self.getSampleWeightCol(),
                compress_sparse=self.getCompressSparseCols(),
                partitions_per_process=self.getPartitionsPerProcess(),
                verbose=self.getVerbose()) as idx:
            train_rows, val_rows, metadata, avg_row_size = \
                util.get_dataset_properties(store, idx)
            return self._fit_on_prepared_data(
                backend, train_rows, val_rows, metadata, avg_row_size, idx)

    def fit_on_parquet(self, params: Optional[dict] = None):
        """Train on Parquet already staged at the store's train path
        (reference: estimator.py:37-49)."""
        if params:
            return self.copy(params).fit_on_parquet()
        backend = self._get_or_create_backend()
        store = self._require_store()
        train_rows, val_rows, metadata, avg_row_size = \
            util.get_simple_meta_from_parquet(
                store, label_columns=self.getLabelCols(),
                feature_columns=self.getFeatureCols(),
                sample_weight_col=self.getSampleWeightCol())
        return self._fit_on_prepared_data(
            backend, train_rows, val_rows, metadata, avg_row_size, 0)

    # -- shared plumbing -----------------------------------------------------

    def _require_store(self):
        store = self.getStore()
        if store is None:
            raise ValueError("estimator needs a store "
                             "(Store.create(prefix_path))")
        return store

    def _get_or_create_backend(self):
        backend = self.getBackend()
        if backend is None:
            from horovod_tpu.spark.common.backend import SparkBackend
            backend = SparkBackend(self.getNumProc(),
                                   verbose=self.getVerbose())
        elif self.getNumProc() is not None:
            raise ValueError('at most one of "backend" and "num_proc" '
                             'may be specified')
        return backend

    def _run_id(self) -> str:
        run_id = self.getRunId()
        if run_id is None:
            run_id = "run_" + uuid.uuid4().hex[:10]
            self.setRunId(run_id)
        return run_id

    def _has_checkpoint(self, run_id: str) -> bool:
        store = self.getStore()
        path = store.get_checkpoint_path(run_id)
        return path is not None and store.exists(path)

    def _fit_on_prepared_data(self, backend, train_rows, val_rows, metadata,
                              avg_row_size, dataset_idx):
        raise NotImplementedError()


class HorovodModel(ModelParams):
    def transform(self, df, params: Optional[dict] = None):
        """Add prediction columns (``<label>__output`` by default) to a
        pandas or pyspark DataFrame (reference: estimator.py:97-117)."""
        if params:
            return self.copy(params).transform(df)
        if util._is_spark_df(df):
            return self._transform_spark(df)
        return self._transform_pandas(df.copy())

    # -- frameworks implement: batch predictions for a feature matrix -------

    def _predict_batch(self, features: np.ndarray) -> np.ndarray:
        """(rows, features) float32 -> (rows, output_dim) predictions."""
        raise NotImplementedError()

    def _transform_pandas(self, pdf):
        feats = util.assemble_features(pdf, self._get("feature_cols"))
        preds = np.asarray(self._predict_batch(feats))
        out_cols = self.getOutputCols()
        preds = preds.reshape(len(pdf), len(out_cols), -1)
        for j, col in enumerate(out_cols):
            block = preds[:, j]
            pdf[col] = list(block) if block.shape[-1] > 1 \
                else block.ravel()
        return pdf

    def _transform_spark(self, df):
        import pandas as pd  # noqa: F401 — mapInPandas contract

        model = self

        def _predict(iterator):
            for pdf in iterator:
                yield model._transform_pandas(pdf)

        # probe one row on the driver to learn each output column's shape
        # — multi-output models yield array columns, not doubles
        probe = self._transform_pandas(df.limit(1).toPandas())

        def _field(col):
            first = probe[col].iloc[0]
            kind = "array<double>" if isinstance(
                first, (list, tuple, np.ndarray)) else "double"
            return f"`{col}` {kind}"

        out_fields = ", ".join(_field(c) for c in self.getOutputCols())
        schema = f"{df.schema.simpleString()[7:-1]}, {out_fields}"
        return df.mapInPandas(_predict, schema=schema)
