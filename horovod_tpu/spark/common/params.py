"""Estimator parameter surface.

Reference analog: horovod/spark/common/params.py:24-300 (EstimatorParams /
ModelParams — pyspark.ml Param machinery with setX/getX accessors). The
TPU build keeps the accessor surface (the part user code touches) over
plain attributes, so the estimators import and run without pyspark; when a
Spark session is around they still consume/produce real Spark DataFrames.
"""

from __future__ import annotations

from typing import Any, Dict


def _accessor_name(param: str) -> str:
    return "".join(p.capitalize() for p in param.split("_"))


class _ParamsBase:
    """Plain-attribute param store with generated reference-style
    ``setFooBar``/``getFooBar`` accessors and keyword construction."""

    _params: Dict[str, Any] = {}

    def __init__(self, **kwargs):
        defaults = {}
        for klass in reversed(type(self).__mro__):
            defaults.update(getattr(klass, "_params", {}))
        self._values = dict(defaults)
        self.setParams(**kwargs)

    def setParams(self, **kwargs):
        for k, v in kwargs.items():
            if k not in self._values:
                raise TypeError(f"unknown parameter {k!r} for "
                                f"{type(self).__name__}")
            self._values[k] = v
        return self

    def _get(self, param: str):
        return self._values[param]

    def _set_value(self, param: str, value):
        self._values[param] = value
        return self

    def copy(self, extra: Dict[str, Any] = None):
        import copy as _copy
        dup = _copy.copy(self)
        dup._values = dict(self._values)
        if extra:
            dup.setParams(**extra)
        return dup

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        for param in cls.__dict__.get("_params", {}):
            acc = _accessor_name(param)
            # default-arg binding: each accessor closes over its own param;
            # hasattr (not cls.__dict__) so a subclass re-declaring a param
            # never shadows a hand-written inherited accessor
            if not hasattr(cls, f"get{acc}"):
                setattr(cls, f"get{acc}",
                        lambda self, _p=param: self._get(_p))
            if not hasattr(cls, f"set{acc}"):
                setattr(cls, f"set{acc}",
                        lambda self, value, _p=param:
                        self._set_value(_p, value))


class EstimatorParams(_ParamsBase):
    """Reference: params.py EstimatorParams (field-for-field; Petastorm
    reader-pool knobs dropped with the Petastorm de-scope)."""

    _params = {
        "num_proc": None,
        "backend": None,
        "store": None,
        "model": None,
        "optimizer": None,
        "loss": None,
        "loss_weights": None,
        "metrics": [],
        "feature_cols": None,
        "label_cols": None,
        "sample_weight_col": None,
        "validation": None,
        "callbacks": [],
        "batch_size": 32,
        "val_batch_size": None,
        "epochs": 1,
        "train_steps_per_epoch": None,
        "validation_steps_per_epoch": None,
        "shuffle_buffer_size": None,
        "verbose": 1,
        "partitions_per_process": None,
        "run_id": None,
        "transformation_fn": None,
        "label_shapes": None,
        "gradient_compression": None,
        "compress_sparse_cols": False,
        "backward_passes_per_step": 1,
    }


class ModelParams(_ParamsBase):
    """Reference: params.py ModelParams."""

    _params = {
        "history": None,
        "model": None,
        "feature_cols": None,
        "label_cols": None,
        "output_cols": None,
        "run_id": None,
        "metadata": None,
    }

    def setOutputCols(self, value):
        return self._set_value("output_cols", value)

    def getOutputCols(self):
        out = self._get("output_cols")
        if out is None:
            out = [f"{c}__output" for c in (self._get("label_cols") or [])]
        return out

    def getHistory(self):
        """Training history dict, e.g. ``{"loss": [...]}`` (reference:
        keras estimator getHistory)."""
        return self._get("history")
