"""Storage abstraction for estimator data, checkpoints, and logs.

Reference analog: horovod/spark/common/store.py:32-456 (Store /
FilesystemStore / LocalStore) — the surface the estimators program
against: where prepared Parquet shards live, where each run's checkpoint
and logs go, and how a training process syncs its local outputs back.

TPU-native scope: the data plane is pyarrow on a filesystem path. A
local/NFS path covers single-host and shared-filesystem clusters (the
common TPU-pod shape — pods mount shared storage); HDFS/S3/DBFS drivers
are out of scope and `Store.create` says so loudly rather than silently
degrading.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Optional


class Store:
    """Abstract run/data layout (reference: store.py:32-150)."""

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError()

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError()

    def get_test_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError()

    def is_parquet_dataset(self, path: str) -> bool:
        raise NotImplementedError()

    def saving_runs(self) -> bool:
        raise NotImplementedError()

    def get_runs_path(self) -> str:
        raise NotImplementedError()

    def get_run_path(self, run_id: str) -> str:
        raise NotImplementedError()

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError()

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError()

    def exists(self, path: str) -> bool:
        raise NotImplementedError()

    def read(self, path: str) -> bytes:
        raise NotImplementedError()

    def write(self, path: str, data: bytes):
        raise NotImplementedError()

    def get_local_output_dir_fn(self, run_id: str):
        """Context manager factory: a scratch dir the training process can
        write into; sync_fn ships it to the run path."""
        raise NotImplementedError()

    def sync_fn(self, run_id: str):
        """Returns fn(local_run_path) that syncs local outputs to the
        store's run path."""
        raise NotImplementedError()

    @staticmethod
    def create(prefix_path: str, *args, **kwargs) -> "Store":
        """Pick a store for the path (reference: store.py:143-150). Only
        filesystem paths are supported; remote schemes raise."""
        for scheme in ("hdfs://", "s3://", "s3a://", "dbfs:/", "gs://"):
            if prefix_path.startswith(scheme):
                raise ValueError(
                    f"unsupported store scheme {scheme!r}: horovod_tpu "
                    "estimators use filesystem stores (local or "
                    "cluster-shared mounts); stage remote data to a "
                    "mounted path first")
        return LocalStore(prefix_path, *args, **kwargs)


class FilesystemStore(Store):
    """Path layout shared by all filesystem stores (reference:
    store.py:153-273)."""

    def __init__(self, prefix_path: str,
                 train_path: Optional[str] = None,
                 val_path: Optional[str] = None,
                 test_path: Optional[str] = None,
                 runs_path: Optional[str] = None,
                 save_runs: bool = True):
        self.prefix_path = prefix_path.rstrip("/")
        self._train_path = train_path or os.path.join(
            self.prefix_path, "intermediate_train_data")
        self._val_path = val_path or os.path.join(
            self.prefix_path, "intermediate_val_data")
        self._test_path = test_path or os.path.join(
            self.prefix_path, "intermediate_test_data")
        self._runs_path = runs_path or os.path.join(
            self.prefix_path, "runs")
        self._save_runs = save_runs

    def _indexed(self, path: str, idx: Optional[int]) -> str:
        return path if idx is None else f"{path}.{idx}"

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        return self._indexed(self._train_path, idx)

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        return self._indexed(self._val_path, idx)

    def get_test_data_path(self, idx: Optional[int] = None) -> str:
        return self._indexed(self._test_path, idx)

    def is_parquet_dataset(self, path: str) -> bool:
        try:
            import pyarrow.parquet as pq
            pq.ParquetDataset(path)
            return True
        except Exception:  # noqa: BLE001 — absent/corrupt = not a dataset
            return False

    def saving_runs(self) -> bool:
        return self._save_runs

    def get_runs_path(self) -> str:
        return self._runs_path

    def get_run_path(self, run_id: str) -> str:
        return os.path.join(self._runs_path, run_id)

    def get_checkpoint_path(self, run_id: str) -> Optional[str]:
        return os.path.join(self.get_run_path(run_id),
                            self.get_checkpoint_filename()) \
            if self._save_runs else None

    def get_logs_path(self, run_id: str) -> Optional[str]:
        return os.path.join(self.get_run_path(run_id),
                            self.get_logs_subdir()) \
            if self._save_runs else None

    def get_checkpoint_filename(self) -> str:
        return "checkpoint.pkl"

    def get_logs_subdir(self) -> str:
        return "logs"


class LocalStore(FilesystemStore):
    """Local (or cluster-shared mount) filesystem store (reference:
    store.py:276-318)."""

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # atomic: readers never see partial bytes

    def get_local_output_dir_fn(self, run_id: str):
        import contextlib

        @contextlib.contextmanager
        def local_run_path():
            d = tempfile.mkdtemp(prefix=f"hvdtpu_run_{run_id}_")
            try:
                yield d
            finally:
                shutil.rmtree(d, ignore_errors=True)

        return local_run_path

    def sync_fn(self, run_id: str):
        run_path = self.get_run_path(run_id)

        def fn(local_run_path: str):
            os.makedirs(run_path, exist_ok=True)
            shutil.copytree(local_run_path, run_path, dirs_exist_ok=True)

        return fn
