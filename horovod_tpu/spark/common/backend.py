"""Training-job backends for the estimators.

Reference analog: horovod/spark/common/backend.py (Backend / SparkBackend
— "run this fn on num_proc coordinated processes"). The TPU build adds a
LocalBackend over the launcher's local-process core, so estimators train
without any cluster scheduler (single TPU host, notebooks, CI).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional


class Backend:
    def num_processes(self) -> int:
        raise NotImplementedError()

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[dict] = None) -> List[Any]:
        raise NotImplementedError()


class SparkBackend(Backend):
    """Barrier-stage executor backend (reference: backend.py SparkBackend),
    delegating to horovod_tpu.spark.run."""

    def __init__(self, num_proc: Optional[int] = None, spark_context=None,
                 verbose: int = 0):
        self._num_proc = num_proc
        self._sc = spark_context
        self._verbose = verbose

    def _context(self):
        if self._sc is None:
            from horovod_tpu.spark import _default_spark_context
            self._sc = _default_spark_context()
        return self._sc

    def num_processes(self) -> int:
        return self._num_proc or self._context().defaultParallelism

    def run(self, fn, args=(), kwargs=None):
        from horovod_tpu import spark as hvd_spark
        return hvd_spark.run(fn, args=args, kwargs=kwargs,
                             num_proc=self.num_processes(),
                             spark_context=self._context(),
                             verbose=bool(self._verbose))


class LocalBackend(Backend):
    """Local-process backend: the estimator's scheduler-free fallback."""

    def __init__(self, num_proc: int = 1, verbose: int = 0):
        self._num_proc = num_proc
        self._verbose = verbose

    def num_processes(self) -> int:
        return self._num_proc

    def run(self, fn, args=(), kwargs=None):
        from horovod_tpu.runner.cluster_job import (ClusterJobSpec,
                                                    run_local_processes)
        spec = ClusterJobSpec(self._num_proc, controller_addr="127.0.0.1")
        return run_local_processes(spec, fn, args, kwargs or {})
