"""Shared estimator infrastructure (reference: horovod/spark/common/)."""

from horovod_tpu.spark.common.backend import (  # noqa: F401
    Backend, LocalBackend, SparkBackend,
)
from horovod_tpu.spark.common.estimator import (  # noqa: F401
    HorovodEstimator, HorovodModel,
)
from horovod_tpu.spark.common.store import (  # noqa: F401
    FilesystemStore, LocalStore, Store,
)
