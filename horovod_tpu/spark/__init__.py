"""Spark integration: run a horovod_tpu job on Spark executors, or fit a
model on a DataFrame through the estimator stack.

Reference analog: horovod/spark/runner.py:195-302 — ``horovod.spark.run(fn,
num_proc=N)`` schedules N simultaneous tasks (a barrier stage), wires the
coordination env into each, executes ``fn`` and returns the per-rank
results — plus the estimator surface (spark/common/estimator.py,
spark/keras/, spark/torch/): ``KerasEstimator(...).fit(df)`` returns a
model transformer. The data plane is pyarrow Parquet + numpy (Petastorm
de-scoped); estimators accept pandas DataFrames too, so they run without
a Spark session.

pyspark is imported lazily: the module is importable (and the orchestration
testable via the local-process backend) without it.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from horovod_tpu.runner.cluster_job import ClusterJobSpec, task_body
from horovod_tpu.spark.common import (  # noqa: F401
    Backend, LocalBackend, SparkBackend, Store, LocalStore,
)


def _default_spark_context():
    try:
        import pyspark
    except ImportError as e:
        raise RuntimeError(
            "horovod_tpu.spark.run needs pyspark (not installed); use "
            "horovod_tpu.run / hvdrun-tpu for non-Spark clusters") from e
    sc = pyspark.SparkContext._active_spark_context
    if sc is None:
        raise RuntimeError("no active SparkContext; create one first")
    return sc


def run(fn: Callable,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        num_proc: Optional[int] = None,
        spark_context=None,
        extra_env: Optional[dict] = None,
        controller_addr: Optional[str] = None,
        verbose: bool = False) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``num_proc`` Spark tasks as one
    coordinated job; returns results in rank order (reference:
    spark/runner.py:195-302).

    The tasks must start simultaneously — a Spark *barrier* stage
    (``RDD.barrier()``) guarantees it; plain stages could schedule tasks
    sequentially and deadlock the rendezvous.
    """
    kwargs = kwargs or {}
    sc = spark_context if spark_context is not None \
        else _default_spark_context()
    num_proc = num_proc or sc.defaultParallelism
    kv = None
    try:
        if controller_addr is None:
            # dynamic endpoints: rank 0's task allocates+publishes the
            # controller ports on its own host via this driver-side KV —
            # the driver can't pre-pick a free port on a host Spark hasn't
            # even chosen yet
            from horovod_tpu.runner.cluster_job import default_driver_addr
            from horovod_tpu.runner.http_kv import KVServer
            kv = KVServer().start()
            spec = ClusterJobSpec(
                num_proc, extra_env=extra_env,
                rendezvous=(default_driver_addr(), kv.port))
        else:
            spec = ClusterJobSpec(num_proc, controller_addr=controller_addr,
                                  extra_env=extra_env)
        envs = [spec.worker_env(r) for r in range(num_proc)]

        def _task(index, _iterator):
            yield index, task_body(envs[index], fn, args, kwargs)

        rdd = sc.parallelize(range(num_proc), num_proc)
        pairs = rdd.barrier().mapPartitionsWithIndex(_task).collect()
    finally:
        if kv is not None:
            kv.stop()
    results = dict(pairs)
    missing = [r for r in range(num_proc) if r not in results]
    if missing:
        raise RuntimeError(f"spark job returned no result for ranks "
                           f"{missing}")
    return [results[r] for r in range(num_proc)]
