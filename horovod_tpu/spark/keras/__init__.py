"""KerasEstimator: fit a keras model on a DataFrame, get a transformer.

Reference analog: horovod/spark/keras/estimator.py:106-520 (KerasEstimator
/ KerasModel). Each training process reads its Parquet shard from the
store, wraps the user optimizer in DistributedOptimizer, trains with the
broadcast + metric-average callbacks, and rank 0 checkpoints weights into
the store; the driver rebuilds the model from that checkpoint.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from horovod_tpu.spark.common import util
from horovod_tpu.spark.common.estimator import HorovodEstimator, HorovodModel


def _resolve_compression(name):
    from horovod_tpu.tensorflow.compression import Compression
    if name is None or name == "none":
        return Compression.none
    return getattr(Compression, name)


def _keras_train_fn(payload: dict):
    """Runs on every backend process (top-level so schedulers pickle it by
    reference)."""
    import cloudpickle
    import tensorflow as tf  # noqa: F401 — keras backend
    import horovod_tpu.tensorflow.keras as hvd

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    store = payload["store"]
    run_id = payload["run_id"]

    model = cloudpickle.loads(payload["model"])
    optimizer = cloudpickle.loads(payload["optimizer"])
    loss = cloudpickle.loads(payload["loss"])
    metrics = cloudpickle.loads(payload["metrics"]) or []
    user_callbacks = cloudpickle.loads(payload["callbacks"]) or []

    dist_opt = hvd.DistributedOptimizer(
        optimizer,
        compression=_resolve_compression(payload["compression"]),
        backward_passes_per_step=payload["backward_passes_per_step"])
    model.compile(optimizer=dist_opt, loss=loss,
                  loss_weights=payload["loss_weights"], metrics=metrics)

    pdf = util.read_shard(payload["train_path"], rank, size)
    x = util.assemble_features(pdf, payload["feature_columns"])
    y = util.assemble_labels(pdf, payload["label_columns"])
    sample_weight = None
    if payload["sample_weight_col"]:
        sample_weight = np.asarray(
            pdf[payload["sample_weight_col"]].to_numpy(), np.float32)
    val_data = None
    if payload["val_path"] is not None:
        vdf = util.read_shard(payload["val_path"], rank, size)
        if len(vdf):
            val_data = (util.assemble_features(vdf,
                                               payload["feature_columns"]),
                        util.assemble_labels(vdf,
                                             payload["label_columns"]))

    callbacks = [hvd.callbacks.BroadcastGlobalVariablesCallback(0),
                 hvd.callbacks.MetricAverageCallback()] + user_callbacks
    history = model.fit(
        x, y, sample_weight=sample_weight,
        batch_size=payload["batch_size"],
        epochs=payload["epochs"],
        steps_per_epoch=payload["train_steps_per_epoch"],
        validation_data=val_data,
        validation_steps=payload["validation_steps_per_epoch"],
        validation_batch_size=payload["val_batch_size"],
        callbacks=callbacks,
        shuffle=True,
        verbose=payload["verbose"] if rank == 0 else 0)

    if rank == 0:
        ckpt = store.get_checkpoint_path(run_id)
        if ckpt is not None:
            store.write(ckpt, cloudpickle.dumps(model.get_weights()))
    hvd.shutdown()
    return {k: [float(v) for v in vs] for k, vs in history.history.items()}


class KerasEstimator(HorovodEstimator):
    """Reference: spark/keras/estimator.py:106-390. Construct with the
    same keywords (model=, optimizer=, loss=, store=, feature_cols=,
    label_cols=, batch_size=, epochs=, ...)."""

    def _fit_on_prepared_data(self, backend, train_rows, val_rows, metadata,
                              avg_row_size, dataset_idx):
        import cloudpickle

        _ = (train_rows, val_rows, avg_row_size)
        store = self._require_store()
        run_id = self._run_id()
        model = self.getModel()
        if model is None or self.getOptimizer() is None or \
                self.getLoss() is None:
            raise ValueError("KerasEstimator needs model=, optimizer=, "
                             "and loss=")
        val_path = store.get_val_data_path(dataset_idx)
        payload = {
            "store": store,
            "run_id": run_id,
            "train_path": store.get_train_data_path(dataset_idx),
            "val_path": val_path if store.exists(val_path) else None,
            "feature_columns": self.getFeatureCols(),
            "label_columns": self.getLabelCols(),
            "sample_weight_col": self.getSampleWeightCol(),
            "model": cloudpickle.dumps(model),
            "optimizer": cloudpickle.dumps(self.getOptimizer()),
            "loss": cloudpickle.dumps(self.getLoss()),
            "loss_weights": self.getLossWeights(),
            "metrics": cloudpickle.dumps(self.getMetrics()),
            "callbacks": cloudpickle.dumps(self.getCallbacks()),
            "batch_size": self.getBatchSize(),
            "val_batch_size": self.getValBatchSize(),
            "epochs": self.getEpochs(),
            "train_steps_per_epoch": self.getTrainStepsPerEpoch(),
            "validation_steps_per_epoch": self.getValidationStepsPerEpoch(),
            "compression": self.getGradientCompression(),
            "backward_passes_per_step": self.getBackwardPassesPerStep(),
            "verbose": self.getVerbose(),
        }
        results = backend.run(_keras_train_fn, args=(payload,))
        history = results[0]
        return self._create_model(history, run_id, metadata)

    def _create_model(self, history, run_id, metadata):
        import cloudpickle

        store = self._require_store()
        ckpt = store.get_checkpoint_path(run_id)
        trained = cloudpickle.loads(cloudpickle.dumps(self.getModel()))
        if ckpt is not None and store.exists(ckpt):
            trained.set_weights(cloudpickle.loads(store.read(ckpt)))
        return KerasModel(model=trained, history=history,
                          feature_cols=self.getFeatureCols(),
                          label_cols=self.getLabelCols(),
                          run_id=run_id, metadata=metadata)


class KerasModel(HorovodModel):
    """Transformer over a trained keras model (reference:
    spark/keras/estimator.py:392-520)."""

    def _predict_batch(self, features: np.ndarray) -> np.ndarray:
        model = self._get("model")
        preds = model.predict(features, verbose=0)
        return np.asarray(preds)

    def keras(self):
        """The underlying trained keras model (reference parity:
        KerasModel.getModel())."""
        return self._get("model")
