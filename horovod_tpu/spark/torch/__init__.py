"""TorchEstimator: fit a torch model on a DataFrame, get a transformer.

Reference analog: horovod/spark/torch/estimator.py:91-434 (TorchEstimator
/ TorchModel). The model and its bound optimizer serialize together (one
cloudpickle payload, so parameter identity survives); each process trains
its Parquet shard with the torch DistributedOptimizer and broadcast-
synchronized initial state; rank 0 checkpoints ``model.state_dict()``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from horovod_tpu.spark.common import util
from horovod_tpu.spark.common.estimator import HorovodEstimator, HorovodModel
from horovod_tpu.spark.common.params import EstimatorParams, ModelParams


def _resolve_compression(name):
    from horovod_tpu.torch.compression import Compression
    if name is None or name == "none":
        return Compression.none
    return getattr(Compression, name)


def _reshape_inputs(x: np.ndarray, input_shapes):
    import torch
    t = torch.as_tensor(x)
    if input_shapes:
        if len(input_shapes) == 1:
            return [t.reshape(input_shapes[0])]
        # multiple inputs: split the flat feature axis by shape sizes
        outs, off = [], 0
        for shape in input_shapes:
            n = int(np.prod([d for d in shape if d != -1]))
            outs.append(t[:, off:off + n].reshape(shape))
            off += n
        return outs
    return [t]


def _torch_train_fn(payload: dict):
    """Runs on every backend process."""
    import cloudpickle
    import torch
    import horovod_tpu.torch as hvd

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    store = payload["store"]
    run_id = payload["run_id"]

    model, optimizer = cloudpickle.loads(payload["model_opt"])
    loss_fns = cloudpickle.loads(payload["loss"])
    if not isinstance(loss_fns, (list, tuple)):
        loss_fns = [loss_fns]
    loss_weights = payload["loss_weights"] or [1.0] * len(loss_fns)

    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)
    dist_opt = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=_resolve_compression(payload["compression"]),
        backward_passes_per_step=payload["backward_passes_per_step"])

    pdf = util.read_shard(payload["train_path"], rank, size)
    x = util.assemble_features(pdf, payload["feature_columns"])
    y = util.assemble_labels(pdf, payload["label_columns"])
    sw = None
    if payload["sample_weight_col"]:
        sw = np.asarray(pdf[payload["sample_weight_col"]].to_numpy(),
                        np.float32)

    batch = payload["batch_size"]
    label_shapes = payload["label_shapes"]
    history = {"loss": []}
    steps_cap = payload["train_steps_per_epoch"]
    model.train()
    for _epoch in range(payload["epochs"]):
        perm = np.random.RandomState(_epoch).permutation(len(x))
        epoch_loss, steps = 0.0, 0
        for s in range(0, len(x), batch):
            if steps_cap is not None and steps >= steps_cap:
                break
            idx = perm[s:s + batch]
            inputs = _reshape_inputs(x[idx], payload["input_shapes"])
            target = torch.as_tensor(y[idx])
            if label_shapes:
                target = target.reshape(label_shapes[0])
            dist_opt.zero_grad()
            out = model(*inputs)
            outs = out if isinstance(out, (list, tuple)) else [out]
            loss = sum(w * fn(o, target)
                       for w, fn, o in zip(loss_weights, loss_fns, outs))
            if sw is not None:
                loss = loss * float(np.mean(sw[idx]))
            loss.backward()
            dist_opt.step()
            epoch_loss += float(loss.detach())
            steps += 1
        avg = epoch_loss / max(steps, 1)
        history["loss"].append(float(hvd.allreduce(
            torch.tensor(avg), name=f"epoch_loss_{_epoch}")))

    if rank == 0:
        ckpt = store.get_checkpoint_path(run_id)
        if ckpt is not None:
            store.write(ckpt, cloudpickle.dumps(
                {k: v.cpu().numpy() for k, v in model.state_dict().items()}))
    hvd.shutdown()
    return history


class TorchEstimator(HorovodEstimator):
    """Reference: spark/torch/estimator.py:91-325. Extra params over the
    common surface: input_shapes (reshape the assembled feature matrix
    into the model's input tensors), loss_constructors."""

    _params = dict(EstimatorParams._params,
                   input_shapes=None, loss_constructors=None,
                   train_minibatch_fn=None, in_memory_cache_all=False)

    def _get_loss_fns(self):
        loss = self.getLoss()
        if loss is None and self.getLossConstructors():
            ctors = self.getLossConstructors()
            ctors = ctors if isinstance(ctors, (list, tuple)) else [ctors]
            loss = [c() for c in ctors]
        return loss

    def _fit_on_prepared_data(self, backend, train_rows, val_rows, metadata,
                              avg_row_size, dataset_idx):
        import cloudpickle

        _ = (train_rows, val_rows, avg_row_size)
        store = self._require_store()
        run_id = self._run_id()
        model = self.getModel()
        loss = self._get_loss_fns()
        if model is None or self.getOptimizer() is None or loss is None:
            raise ValueError("TorchEstimator needs model=, optimizer=, and "
                             "loss= (or loss_constructors=)")
        val_path = store.get_val_data_path(dataset_idx)
        payload = {
            "store": store,
            "run_id": run_id,
            "train_path": store.get_train_data_path(dataset_idx),
            "val_path": val_path if store.exists(val_path) else None,
            "feature_columns": self.getFeatureCols(),
            "label_columns": self.getLabelCols(),
            "sample_weight_col": self.getSampleWeightCol(),
            # model+optimizer in ONE payload: the optimizer's parameter
            # references must deserialize to the same tensors
            "model_opt": cloudpickle.dumps((model, self.getOptimizer())),
            "loss": cloudpickle.dumps(loss),
            "loss_weights": self.getLossWeights(),
            "batch_size": self.getBatchSize(),
            "epochs": self.getEpochs(),
            "train_steps_per_epoch": self.getTrainStepsPerEpoch(),
            "input_shapes": self.getInputShapes(),
            "label_shapes": self.getLabelShapes(),
            "compression": self.getGradientCompression(),
            "backward_passes_per_step": self.getBackwardPassesPerStep(),
            "verbose": self.getVerbose(),
        }
        results = backend.run(_torch_train_fn, args=(payload,))
        history = results[0]
        return self._create_model(history, run_id, metadata)

    def _create_model(self, history, run_id, metadata):
        import cloudpickle
        import torch

        store = self._require_store()
        ckpt = store.get_checkpoint_path(run_id)
        trained, _opt = cloudpickle.loads(
            cloudpickle.dumps((self.getModel(), None)))
        if ckpt is not None and store.exists(ckpt):
            state = {k: torch.as_tensor(v) for k, v in
                     cloudpickle.loads(store.read(ckpt)).items()}
            trained.load_state_dict(state)
        return TorchModel(model=trained, history=history,
                          feature_cols=self.getFeatureCols(),
                          label_cols=self.getLabelCols(),
                          run_id=run_id, metadata=metadata,
                          input_shapes=self.getInputShapes())


class TorchModel(HorovodModel):
    """Transformer over a trained torch model (reference:
    spark/torch/estimator.py:326-434)."""

    _params = dict(ModelParams._params, input_shapes=None)

    def _predict_batch(self, features: np.ndarray) -> np.ndarray:
        import torch

        model = self._get("model")
        model.eval()
        with torch.no_grad():
            inputs = _reshape_inputs(features, self._get("input_shapes"))
            out = model(*inputs)
        out = out[0] if isinstance(out, (list, tuple)) else out
        return np.asarray(out.cpu().numpy())

    def torch(self):
        """The underlying trained torch module."""
        return self._get("model")
