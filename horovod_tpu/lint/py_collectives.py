"""Python prong: collective-safety rules (HVL001–HVL003), stdlib ``ast``.

The engine's core contract (Horovod's implicit contract, arXiv:1802.05799)
is that every rank submits the same collectives in a compatible order.
The runtime signature hash (PR 5) catches a violation one coordination
cycle after it happens; these rules catch the *shapes of code* that
produce violations at authoring time:

- HVL001 — a collective reachable only when a rank-dependent condition
  holds (``if hvd.rank() == 0: hvd.allreduce(...)``), including the
  early-return form (``if rank() != 0: return`` followed by collectives).
- HVL002 — an ``if/else`` on a rank-dependent condition whose branches
  issue *different* collective sequences (both sides collect, but they
  will never agree on order).
- HVL003 — a broad ``except Exception``/bare ``except`` wrapping
  collective calls without re-raising: it can eat
  ``HorovodInternalError``/``HorovodCorruptedError``, and the fast-abort
  protocol (PR 4) depends on those propagating to every rank's retry
  loop.
"""

from __future__ import annotations

import ast
from pathlib import Path

from horovod_tpu.lint.base import FileReporter, Reporter

# The public collective surface across frontends (jax/tf/torch mpi_ops,
# parallel/collectives, common/eager, keras/torch broadcast helpers).
# `join` is deliberately absent: it exists to be called by a *subset* of
# ranks (early finishers), so rank-dependent reachability is its job.
COLLECTIVE_NAMES = frozenset({
    "allreduce", "allreduce_async", "allreduce_async_",
    "allgather", "allgather_async", "allgather_object",
    "broadcast", "broadcast_async", "broadcast_async_",
    "broadcast_object", "broadcast_variables", "broadcast_parameters",
    "broadcast_optimizer_state", "broadcast_global_variables",
    "alltoall", "alltoall_async",
    "reducescatter", "reducescatter_async",
    "grouped_allreduce", "grouped_allreduce_async",
    "grouped_allgather", "hierarchical_allreduce",
    "quantized_allreduce", "quantized_allgather",
    "quantized_reducescatter",
    "barrier", "metric_average", "sync_batch_norm",
    # completion of an async collective — where HorovodInternalError
    # actually surfaces on the eager path
    "synchronize",
})

# Condition fragments that make a branch rank-dependent.
_RANK_CALL_NAMES = frozenset({"rank", "local_rank", "cross_rank",
                              "axis_rank", "process_index"})
_RANK_VALUE_NAMES = frozenset({"rank", "local_rank", "cross_rank",
                               "is_coordinator", "is_chief", "is_root",
                               "is_master", "root_rank"})

_BROAD_EXC_NAMES = frozenset({"Exception", "BaseException"})


def _call_name(node: ast.Call):
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _is_rank_dependent(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Call) and _call_name(node) in _RANK_CALL_NAMES:
            return True
        if isinstance(node, ast.Attribute) and \
                node.attr in _RANK_VALUE_NAMES:
            return True
        if isinstance(node, ast.Name) and node.id in _RANK_VALUE_NAMES:
            return True
    return False


def _collective_calls(node: ast.AST):
    """Collective Call nodes anywhere under ``node`` (skipping nested
    function/class definitions — their reachability is their own)."""
    out = []

    def visit(n):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return
        if isinstance(n, ast.Call) and _call_name(n) in COLLECTIVE_NAMES:
            out.append(n)
        for child in ast.iter_child_nodes(n):
            visit(child)

    for child in ast.iter_child_nodes(node) if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)) else [node]:
        visit(child)
    return out


def _collective_calls_in_stmts(stmts) -> list:
    out = []
    for s in stmts:
        out.extend(_collective_calls(s))
    return out


def _terminates(stmts) -> bool:
    """Does the block unconditionally leave the enclosing flow?"""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    for node in ([t] if not isinstance(t, ast.Tuple) else t.elts):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
    return any(n in _BROAD_EXC_NAMES for n in names)


def _reraises(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
    return False


class _Checker(ast.NodeVisitor):
    def __init__(self, rep: FileReporter):
        self.rep = rep

    # -- rank-divergent reachability (HVL001/HVL002) --------------------

    def _flag_collectives(self, stmts, why: str):
        for call in _collective_calls_in_stmts(stmts):
            self.rep.add(
                "HVL001", call.lineno,
                f"collective `{_call_name(call)}` is {why} — every rank "
                "must submit the same collectives in the same order "
                "(runtime analog: the coordinator's signature-hash desync "
                "error)")

    def visit_If(self, node: ast.If):
        if not _is_rank_dependent(node.test):
            self.generic_visit(node)
            return
        body_seq = [_call_name(c)
                    for c in _collective_calls_in_stmts(node.body)]
        else_seq = [_call_name(c)
                    for c in _collective_calls_in_stmts(node.orelse)]
        if body_seq and else_seq and body_seq != else_seq:
            self.rep.add(
                "HVL002", node.lineno,
                "rank-dependent if/else issues different collective "
                f"sequences: {body_seq} vs {else_seq} — ranks taking "
                "different branches desynchronize the collective order")
        elif body_seq != else_seq:
            # one-sided: collectives only on one branch
            side = node.body if body_seq else node.orelse
            self._flag_collectives(
                side, "reachable only under a rank-dependent condition")
        # still descend: nested Try/If structure has its own rules; exact
        # duplicates are collapsed by the caller's dedupe
        self.generic_visit(node)

    def visit_While(self, node: ast.While):
        if _is_rank_dependent(node.test):
            self._flag_collectives(
                node.body,
                "looped under a rank-dependent `while` condition")
        self.generic_visit(node)

    def _check_early_exit(self, stmts):
        """``if rank() != 0: return`` (or raise/continue/break) makes every
        later collective in the block rank-divergent."""
        divergent_since = None
        for stmt in stmts:
            if divergent_since is not None:
                for call in _collective_calls(stmt):
                    self.rep.add(
                        "HVL001", call.lineno,
                        f"collective `{_call_name(call)}` follows a "
                        "rank-dependent early exit at line "
                        f"{divergent_since} — only a subset of ranks "
                        "reaches it")
            elif isinstance(stmt, ast.If) and \
                    _is_rank_dependent(stmt.test) and \
                    _terminates(stmt.body) and not stmt.orelse:
                divergent_since = stmt.lineno

    def visit_FunctionDef(self, node):
        self._check_early_exit(node.body)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- swallowed abort (HVL003) ---------------------------------------

    def visit_Try(self, node: ast.Try):
        body_collectives = _collective_calls_in_stmts(node.body)
        if body_collectives:
            for handler in node.handlers:
                if _is_broad_handler(handler) and not _reraises(handler):
                    names = sorted({_call_name(c)
                                    for c in body_collectives})
                    self.rep.add(
                        "HVL003", handler.lineno,
                        "broad except around collective call(s) "
                        f"{names} neither re-raises nor narrows: it can "
                        "swallow HorovodInternalError and strand the "
                        "other ranks (fast-abort and elastic recovery "
                        "depend on it propagating)")
        self.generic_visit(node)


def check_python_collectives(rep: Reporter, path: Path):
    fr = rep.scan_file(path)
    try:
        tree = ast.parse(fr.text, filename=str(path))
    except SyntaxError as e:
        fr.add("HVL001", e.lineno or 1, f"file does not parse: {e.msg}")
        return
    _Checker(fr).visit(tree)
