"""ABI-drift rule (HVL104).

``engine/src/c_api.cc`` and ``engine/bindings.py`` describe the same C
ABI from two sides; the only runtime guard is the version handshake,
which catches a *stale build* but not a *drifted source pair* (a new
export nobody bound, a removed export still declared, an argtypes list
whose arity no longer matches the C signature — the classic silent-
corruption ctypes bug). HVL104 parses both sides statically and flags:

- ABI version literal mismatch (``hvdtpu_abi_version`` vs ``ABI_VERSION``);
- exported ``hvdtpu_*`` symbols never referenced in the bindings;
- bindings references to symbols the C side does not export;
- ``lib.hvdtpu_x.argtypes = [...]`` lists whose length differs from the
  C parameter count.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Tuple

from horovod_tpu.lint.base import Reporter
# One parser for the C side of the ABI: the regexes/param counter live
# in verify/engine_constants.py (the protocol specs parse the same
# sources), so HVL104 and the specs can never disagree about what the
# ABI *is*.
from horovod_tpu.verify.engine_constants import (_ABI_RE, _EXPORT_RE,
                                                 _param_count)


def parse_c_side(text: str) -> Tuple[int, Dict[str, Tuple[int, int]]]:
    """(abi_version or -1, {symbol: (param_count, line)})."""
    m = _ABI_RE.search(text)
    abi = int(m.group(1)) if m else -1
    exports: Dict[str, Tuple[int, int]] = {}
    for m in _EXPORT_RE.finditer(text):
        line = text[:m.start()].count("\n") + 1
        exports[m.group(1)] = (_param_count(text, m.end() - 1), line)
    return abi, exports


def parse_bindings(tree: ast.AST) \
        -> Tuple[int, int, Dict[str, Tuple[int, int]], Dict[str, int]]:
    """(ABI_VERSION or -1, its line, {symbol: (argtypes len, line)},
    {referenced symbol: first line})."""
    abi, abi_line = -1, 1
    argtype_lens: Dict[str, Tuple[int, int]] = {}
    referenced: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id == "ABI_VERSION" and \
                    isinstance(node.value, ast.Constant):
                abi, abi_line = int(node.value.value), node.lineno
            if isinstance(t, ast.Attribute) and t.attr == "argtypes" and \
                    isinstance(t.value, ast.Attribute) and \
                    t.value.attr.startswith("hvdtpu_") and \
                    isinstance(node.value, ast.List):
                argtype_lens[t.value.attr] = (len(node.value.elts),
                                              node.lineno)
        if isinstance(node, ast.Attribute) and \
                node.attr.startswith("hvdtpu_"):
            referenced.setdefault(node.attr, node.lineno)
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                node.value.startswith("hvdtpu_"):
            referenced.setdefault(node.value, node.lineno)
    return abi, abi_line, argtype_lens, referenced


def check_abi_sync(rep: Reporter, c_api: Path, bindings: Path):
    """HVL104 over one (c_api.cc, bindings.py) pair."""
    if not c_api.exists() or not bindings.exists():
        return
    c_fr = rep.scan_file(c_api)
    b_fr = rep.scan_file(bindings)
    c_abi, exports = parse_c_side(c_fr.text)
    try:
        tree = ast.parse(b_fr.text, filename=str(bindings))
    except SyntaxError:
        return
    b_abi, b_abi_line, argtype_lens, referenced = parse_bindings(tree)

    if c_abi != b_abi:
        b_fr.add("HVL104", b_abi_line,
                 f"ABI version drift: bindings declare {b_abi} but "
                 f"{c_api.name} returns {c_abi} — bump both together "
                 "(the load-time handshake only catches stale builds, "
                 "not drifted sources)")
    for sym, (nargs, line) in sorted(exports.items()):
        if sym == "hvdtpu_abi_version":
            continue  # bound reflectively inside load_library itself
        if sym not in referenced:
            c_fr.add("HVL104", line,
                     f"C export `{sym}` is never referenced in "
                     f"{bindings.name} — an unbound ABI surface drifts "
                     "silently")
    for sym, line in sorted(referenced.items()):
        if sym not in exports:
            b_fr.add("HVL104", line,
                     f"bindings reference `{sym}` but {c_api.name} does "
                     "not export it")
    for sym, (nargs, line) in sorted(argtype_lens.items()):
        if sym in exports and exports[sym][0] != nargs:
            b_fr.add("HVL104", line,
                     f"`{sym}.argtypes` declares {nargs} parameter(s) "
                     f"but the C signature takes {exports[sym][0]} — "
                     "ctypes will silently corrupt the call frame")
