"""Shared lint infrastructure: findings, rule metadata, suppression
comments, and file discovery.

Suppression syntax (both prongs):

- ``# hvd-lint: disable=HVL001`` (Python) /
  ``// hvd-lint: disable=HVL101`` (C++) on the flagged line or the line
  directly above suppresses the listed rule(s) there; comma-separate
  several ids; omitting ``=ids`` suppresses every rule on that line.
- ``# hvd-lint: disable-file=HVL003`` anywhere in the first 10 lines
  suppresses the rule(s) for the whole file.

Suppressions are deliberate, reviewable artifacts — the point of the
static prong is that every exception to a contract is written down next
to the code that needs it.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence

# rule id -> one-line description (the rule table in docs/DESIGN.md is
# generated from this; keep the wording doc-ready)
RULES: Dict[str, str] = {
    "HVL001": "collective reachable only under rank-dependent control flow "
              "(static counterpart of the runtime desync detector)",
    "HVL002": "if/else branches on a rank-dependent condition issue "
              "different collective sequences",
    "HVL003": "broad except can swallow HorovodInternalError around a "
              "collective without re-raising (breaks fast-abort)",
    "HVL004": "direct os.environ read of a HOROVOD_* variable — use the "
              "typed accessors in common/env_registry.py",
    "HVL005": "HOROVOD_* name not in the env registry (typo suggestions "
              "by edit distance)",
    "HVL006": "docs/DESIGN.md env table out of sync with the registry "
              "(regenerate with --write-env-table)",
    "HVL007": "raw string KV-key construction outside the typed key "
              "registry (common/kv_keys.py)",
    "HVL008": "driver-originated KV write missing an epoch claim "
              "(invisible to split-brain fencing and conformance replay)",
    "HVL101": "raw wait_for/wait_until/pthread_cond_clockwait outside "
              "CvWaitFor (gcc-10 libtsan cannot model clockwait)",
    "HVL102": "lock-order cycle in the static lock graph (deadlock "
              "hazard)",
    "HVL103": "atomics discipline: hot-path counters must use "
              "memory_order_relaxed; cross-thread flags must be "
              "std::atomic",
    "HVL104": "ABI drift between engine/src/c_api.cc exports / ABI "
              "version and engine/bindings.py ctypes declarations",
}

_DISABLE_RE = re.compile(
    r"(?:#|//)\s*hvd-lint:\s*disable(?P<file>-file)?(?:=(?P<ids>[A-Z0-9, ]+))?")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str   # repo-relative, forward slashes
    line: int   # 1-based
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass
class Suppressions:
    """Per-file suppression state parsed once from the source text."""
    file_rules: Optional[set] = None  # None = no file-level disable
    by_line: Dict[int, Optional[set]] = field(default_factory=dict)
    # by_line value None = all rules disabled on that line

    def active(self, rule: str, line: int) -> bool:
        if self.file_rules is not None and (
                not self.file_rules or rule in self.file_rules):
            return True
        for ln in (line, line - 1):
            if ln in self.by_line:
                ids = self.by_line[ln]
                if ids is None or rule in ids:
                    return True
        return False


def parse_suppressions(text: str) -> Suppressions:
    sup = Suppressions()
    for i, raw in enumerate(text.splitlines(), start=1):
        m = _DISABLE_RE.search(raw)
        if not m:
            continue
        ids = m.group("ids")
        rule_set = ({r.strip() for r in ids.split(",") if r.strip()}
                    if ids else None)
        if m.group("file") and i <= 10:
            sup.file_rules = rule_set or set()  # empty set = all rules
        else:
            sup.by_line[i] = rule_set
    return sup


class Reporter:
    """Collects findings, applying suppressions for the file being
    scanned."""

    def __init__(self, repo_root: Path):
        self.repo_root = Path(repo_root)
        self.findings: List[Finding] = []
        self._file_cache: Dict[Path, "FileReporter"] = {}

    def scan_file(self, path: Path) -> "FileReporter":
        # Several rule families scan the same file; read and parse
        # suppressions once per path, not once per rule.
        fr = self._file_cache.get(path)
        if fr is None:
            text = path.read_text(errors="replace")
            fr = self._file_cache[path] = FileReporter(self, path, text)
        return fr

    def add_repo_finding(self, rule: str, path: Path, line: int,
                         message: str):
        """A finding not tied to one scanned file's suppression state
        (e.g. the doc-sync rule)."""
        self.findings.append(
            Finding(rule, self._rel(path), line, message))

    def _rel(self, path: Path) -> str:
        path = Path(path)
        if not path.is_absolute():
            return path.as_posix()
        try:
            return path.resolve().relative_to(
                self.repo_root.resolve()).as_posix()
        except ValueError:
            return path.as_posix()


class FileReporter:
    def __init__(self, parent: Reporter, path: Path, text: str):
        self.parent = parent
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.suppressions = parse_suppressions(text)

    def add(self, rule: str, line: int, message: str):
        if self.suppressions.active(rule, line):
            return
        self.parent.findings.append(
            Finding(rule, self.parent._rel(self.path), line, message))


# Compiled artifacts and caches: scanning these would be slow and
# meaningless, so they are excluded by default (satellite requirement).
DEFAULT_EXCLUDE_DIRS = ("__pycache__", ".git", ".pytest_cache", "node_modules")
DEFAULT_EXCLUDE_DIR_GLOBS = ("build*",)
DEFAULT_EXCLUDE_SUFFIXES = (".o", ".so", ".pyc", ".a", ".d")


def iter_source_files(roots: Sequence[Path],
                      suffixes: Iterable[str],
                      extra_exclude_dirs: Sequence[str] = ()) -> List[Path]:
    """Walk ``roots`` (files or directories) yielding sources with one of
    ``suffixes``, skipping the default exclude list (build*/, __pycache__,
    compiled artifacts) plus ``extra_exclude_dirs`` by name."""
    import fnmatch
    suffixes = tuple(suffixes)
    out: List[Path] = []

    def excluded_dir(name: str) -> bool:
        if name in DEFAULT_EXCLUDE_DIRS or name in extra_exclude_dirs:
            return True
        return any(fnmatch.fnmatch(name, g)
                   for g in DEFAULT_EXCLUDE_DIR_GLOBS)

    def walk(p: Path):
        if p.is_dir():
            if excluded_dir(p.name):
                return
            for child in sorted(p.iterdir()):
                walk(child)
        elif p.suffix in suffixes and \
                p.suffix not in DEFAULT_EXCLUDE_SUFFIXES:
            out.append(p)

    for root in roots:
        root = Path(root)
        if root.exists():
            # explicit file arguments bypass the directory-name excludes
            if root.is_file():
                if root.suffix in suffixes:
                    out.append(root)
            else:
                for child in sorted(root.iterdir()):
                    walk(child)
    return out
