"""Env-var discipline rules (HVL004–HVL006).

Every ``HOROVOD_*`` variable is declared once in
``horovod_tpu/common/env_registry.py``; these rules enforce the three
sides of that contract:

- HVL004 — Python code must read HOROVOD_* through the typed accessors,
  never ``os.environ``/``os.getenv`` directly (writes are allowed — the
  launcher builds child environments by hand).
- HVL005 — any ``HOROVOD_*`` name appearing in the tree (Python string
  literals including docstrings; quoted strings in C++ sources) must be
  a registered name. Unknown names get an edit-distance suggestion, so
  a misspelled cycle-time knob says "did you mean HOROVOD_CYCLE_TIME"
  instead of silently becoming a default at runtime.
- HVL006 — the env table embedded in docs/DESIGN.md between
  ``<!-- env-table:begin -->`` / ``<!-- env-table:end -->`` must equal
  the generated table (``python -m horovod_tpu.lint --write-env-table``).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from horovod_tpu.common.env_registry import REGISTRY, render_env_table
from horovod_tpu.lint.base import Reporter

_ENV_NAME_RE = re.compile(r"\bHOROVOD_[A-Z][A-Z0-9_]+\b")
_CPP_QUOTED_RE = re.compile(r'"(HOROVOD_[A-Z][A-Z0-9_]+)"')

TABLE_BEGIN = "<!-- env-table:begin -->"
TABLE_END = "<!-- env-table:end -->"


def edit_distance(a: str, b: str, cap: int = 4) -> int:
    """Levenshtein with an early-out cap (names are short, candidates
    few)."""
    if abs(len(a) - len(b)) > cap:
        return cap + 1
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[-1] + 1,
                           prev[j - 1] + (ca != cb)))
        if min(cur) > cap:
            return cap + 1
        prev = cur
    return prev[-1]


def nearest_registered(name: str):
    """(best_name, distance) over the registry."""
    best, best_d = None, 10 ** 9
    for cand in REGISTRY:
        d = edit_distance(name, cand)
        if d < best_d:
            best, best_d = cand, d
    return best, best_d


def _unknown_name_message(name: str) -> str:
    best, d = nearest_registered(name)
    if best is not None and d <= 2:
        return (f"`{name}` is not in the env registry — did you mean "
                f"`{best}`? (edit distance {d})")
    return (f"`{name}` is not in the env registry; declare it in "
            "horovod_tpu/common/env_registry.py (name, type, default, "
            "doc) so the docs table and typo check cover it")


def _env_key_literal(node) -> str | None:
    """The HOROVOD_* key of a read expression, if statically known."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith("HOROVOD_"):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values and \
            isinstance(node.values[0], ast.Constant) and \
            str(node.values[0].value).startswith("HOROVOD_"):
        return str(node.values[0].value) + "..."
    return None


def _is_os_environ(node) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "environ"
            and isinstance(node.value, ast.Name) and node.value.id == "os")


class _EnvReadChecker(ast.NodeVisitor):
    def __init__(self, fr):
        self.fr = fr

    def _flag(self, line: int, key: str, how: str):
        self.fr.add(
            "HVL004", line,
            f"direct {how} of `{key}` — route the read through "
            "horovod_tpu.common.env_registry (env_str/env_int/env_float/"
            "env_bool/env_is_set) so typos fail loudly and the docs "
            "table stays complete")

    def visit_Subscript(self, node: ast.Subscript):
        # os.environ["X"] — only reads (Load); writes/deletes are the
        # launcher's job and stay allowed
        if _is_os_environ(node.value) and isinstance(node.ctx, ast.Load):
            key = _env_key_literal(node.slice)
            if key:
                self._flag(node.lineno, key, "os.environ[...] read")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and node.args:
            key = _env_key_literal(node.args[0])
            if key:
                if _is_os_environ(f.value) and f.attr in ("get",
                                                          "setdefault"):
                    self._flag(node.lineno, key, f"os.environ.{f.attr}()")
                elif isinstance(f.value, ast.Name) and f.value.id == "os" \
                        and f.attr == "getenv":
                    self._flag(node.lineno, key, "os.getenv()")
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare):
        # "HOROVOD_X" in os.environ
        if len(node.ops) == 1 and isinstance(node.ops[0],
                                             (ast.In, ast.NotIn)) and \
                _is_os_environ(node.comparators[0]):
            key = _env_key_literal(node.left)
            if key:
                self._flag(node.lineno, key,
                           "`in os.environ` membership test")
        self.generic_visit(node)


def check_python_env(rep: Reporter, path: Path):
    """HVL004 (direct reads) + HVL005 (unknown names in string literals,
    docstrings included) for one Python file."""
    fr = rep.scan_file(path)
    try:
        tree = ast.parse(fr.text, filename=str(path))
    except SyntaxError:
        return  # the collectives checker already reports parse failures
    if path.name != "env_registry.py":
        _EnvReadChecker(fr).visit(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for m in _ENV_NAME_RE.finditer(node.value):
                name = m.group(0)
                if name not in REGISTRY:
                    fr.add("HVL005", node.lineno,
                           _unknown_name_message(name))


def check_cpp_env(rep: Reporter, path: Path):
    """HVL005 for C++ sources: every quoted HOROVOD_* string (getenv keys,
    error messages) must be a registered name."""
    fr = rep.scan_file(path)
    for i, line in enumerate(fr.lines, start=1):
        for m in _CPP_QUOTED_RE.finditer(line):
            name = m.group(1)
            if name not in REGISTRY:
                fr.add("HVL005", i, _unknown_name_message(name))


def check_doc_sync(rep: Reporter, design_md: Path):
    """HVL006: the docs env table must equal the generated one."""
    if not design_md.exists():
        rep.add_repo_finding("HVL006", design_md, 1,
                             "docs/DESIGN.md is missing")
        return
    text = design_md.read_text()
    if TABLE_BEGIN not in text or TABLE_END not in text:
        rep.add_repo_finding(
            "HVL006", design_md, 1,
            f"env-table markers not found ({TABLE_BEGIN} ... {TABLE_END});"
            " run `python -m horovod_tpu.lint --write-env-table`")
        return
    begin = text.index(TABLE_BEGIN) + len(TABLE_BEGIN)
    end = text.index(TABLE_END)
    embedded = text[begin:end].strip("\n")
    expected = render_env_table().strip("\n")
    if embedded != expected:
        line = text[:begin].count("\n") + 1
        emb_rows = {r for r in embedded.splitlines() if r.startswith("| `")}
        exp_rows = {r for r in expected.splitlines() if r.startswith("| `")}

        def names(rows):
            return {r.split("`")[1] for r in rows if "`" in r}
        missing = sorted(names(exp_rows) - names(emb_rows))
        stale = sorted(names(emb_rows) - names(exp_rows))
        detail = []
        if missing:
            detail.append(f"missing from docs: {missing}")
        if stale:
            detail.append(f"stale in docs: {stale}")
        if not detail:
            detail.append("row content drifted (type/default/doc)")
        rep.add_repo_finding(
            "HVL006", design_md, line,
            "env table out of sync with env_registry.py — " +
            "; ".join(detail) +
            " (regenerate: `python -m horovod_tpu.lint --write-env-table`)")


def write_env_table(design_md: Path) -> bool:
    """Replace the embedded table with the generated one. Returns True if
    the file changed."""
    text = design_md.read_text()
    if TABLE_BEGIN not in text or TABLE_END not in text:
        raise SystemExit(
            f"{design_md}: env-table markers not found; add\n"
            f"{TABLE_BEGIN}\n{TABLE_END}\nwhere the table belongs")
    begin = text.index(TABLE_BEGIN) + len(TABLE_BEGIN)
    end = text.index(TABLE_END)
    new = text[:begin] + "\n" + render_env_table() + text[end:]
    if new != text:
        design_md.write_text(new)
        return True
    return False
