"""`hvd-lint` — static collective-safety & engine-concurrency analysis.

Usage::

    hvd-lint [paths...]              # lint (default: the whole repo)
    hvd-lint --rules HVL003,HVL101   # subset of rules
    hvd-lint --lock-graph out.dot    # also emit the lock-order graph
    hvd-lint --write-env-table       # regenerate docs/DESIGN.md env table
    hvd-lint --list-rules
    make lint                        # repo-root convenience target

Exit status: 0 clean, 1 findings, 2 usage error. ``tests/test_lint.py``
runs the full suite on the repository itself and asserts zero findings,
making every rule a permanent tier-1 gate.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from horovod_tpu.lint.abi_rules import check_abi_sync
from horovod_tpu.lint.base import RULES, Finding, Reporter, iter_source_files
from horovod_tpu.lint.cpp_rules import (check_atomics, check_lock_order,
                                        check_raw_cv_wait)
from horovod_tpu.lint.py_collectives import check_python_collectives
from horovod_tpu.lint.py_env import (check_cpp_env, check_doc_sync,
                                     check_python_env, write_env_table)
from horovod_tpu.lint.py_kv import (check_python_kv_epochs,
                                    check_python_kv_keys)

# Repo layout contract: the scan roots relative to the repo root.
PY_ROOTS = ("horovod_tpu", "examples", "bench.py")
CPP_ROOTS = ("horovod_tpu/engine/src", "horovod_tpu/engine/tsan_harness.cc")
DESIGN_MD = "docs/DESIGN.md"
DEFAULT_DOT = "horovod_tpu/engine/build/lock_order.dot"


def find_repo_root(start: Optional[Path] = None) -> Path:
    """The directory holding the ``horovod_tpu`` package (the repo root in
    a checkout; the site dir in an install)."""
    here = Path(__file__).resolve()
    return here.parents[2]


def run_lint(repo_root: Optional[Path] = None,
             paths: Optional[List[Path]] = None,
             rules: Optional[set] = None,
             lock_graph_out: Optional[Path] = None) -> List[Finding]:
    """Run every (selected) rule; returns deduplicated findings sorted by
    path/line. ``paths`` overrides the default scan roots (files or
    directories; Python rules run on .py, C++ rules on .cc/.h)."""
    root = Path(repo_root) if repo_root else find_repo_root()
    rep = Reporter(root)

    if paths:
        py_files = iter_source_files(paths, (".py",))
        cpp_files = iter_source_files(paths, (".cc", ".h", ".cpp", ".hpp"))
        check_docs = False
    else:
        py_files = iter_source_files(
            [root / p for p in PY_ROOTS], (".py",),
            extra_exclude_dirs=("lint_fixtures",))
        cpp_files = iter_source_files(
            [root / p for p in CPP_ROOTS], (".cc", ".h", ".cpp", ".hpp"))
        check_docs = True

    def on(rule: str) -> bool:
        return rules is None or rule in rules

    for f in py_files:
        if on("HVL001") or on("HVL002") or on("HVL003"):
            check_python_collectives(rep, f)
        if on("HVL004") or on("HVL005"):
            check_python_env(rep, f)
        if on("HVL007"):
            check_python_kv_keys(rep, f)
        if on("HVL008"):
            check_python_kv_epochs(rep, f)
    for f in cpp_files:
        if on("HVL101"):
            check_raw_cv_wait(rep, f)
        if on("HVL005"):
            check_cpp_env(rep, f)
        if on("HVL103"):
            check_atomics(rep, f)
    if on("HVL102") and cpp_files:
        check_lock_order(rep, cpp_files, dot_out=lock_graph_out)
    if on("HVL104"):
        # the (c_api.cc, bindings.py) ABI pair: the real one on full-repo
        # runs. For explicit paths, pair candidates by their directory
        # (fixtures ship both halves side by side); a lone half — e.g.
        # `hvd-lint engine/bindings.py` after a bindings edit — is
        # checked against the real repo counterpart rather than silently
        # skipping the rule.
        real_c = root / "horovod_tpu/engine/src/c_api.cc"
        real_b = root / "horovod_tpu/engine/bindings.py"
        if paths:
            pairs: dict = {}
            for c in (f for f in cpp_files if "c_api" in f.name):
                pairs.setdefault(c.parent, [None, None])[0] = c
            for b in (f for f in py_files if "bindings" in f.name):
                pairs.setdefault(b.parent, [None, None])[1] = b
            # dedupe resolved pairs: passing both real halves explicitly
            # puts them in different parent dirs, and each would fall
            # back to the other — one check, not two
            resolved = {(c or real_c, b or real_b)
                        for c, b in pairs.values()}
            for c, b in sorted(resolved):
                check_abi_sync(rep, c, b)
        else:
            check_abi_sync(rep, real_c, real_b)
    if check_docs and on("HVL006"):
        check_doc_sync(rep, root / DESIGN_MD)

    if rules is not None:
        rep.findings = [f for f in rep.findings if f.rule in rules]
    # nested rank-dependent branches can flag the same call twice —
    # collapse exact duplicates, keep stable order
    seen, out = set(), []
    for f in sorted(rep.findings, key=lambda f: (f.path, f.line, f.rule)):
        key = (f.rule, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="hvd-lint",
        description="static collective-safety & engine-concurrency "
                    "analysis for horovod_tpu")
    p.add_argument("paths", nargs="*", type=Path,
                   help="files/directories to scan (default: repo roots "
                        f"{PY_ROOTS} + {CPP_ROOTS} + doc sync)")
    p.add_argument("--rules", help="comma-separated rule ids to run")
    p.add_argument("--lock-graph", type=Path, metavar="OUT.dot",
                   help="write the static lock-order graph (default "
                        f"{DEFAULT_DOT} on full-repo runs)")
    p.add_argument("--write-env-table", action="store_true",
                   help=f"regenerate the env table in {DESIGN_MD} from "
                        "common/env_registry.py, then exit")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--repo-root", type=Path, default=None)
    args = p.parse_args(argv)

    if args.list_rules:
        for rid, doc in sorted(RULES.items()):
            print(f"{rid}  {doc}")
        return 0

    root = args.repo_root or find_repo_root()
    if args.write_env_table:
        changed = write_env_table(root / DESIGN_MD)
        print(f"{DESIGN_MD}: env table "
              f"{'updated' if changed else 'already current'}")
        return 0

    rules = None
    if args.rules:
        rules = {r.strip().upper() for r in args.rules.split(",")}
        unknown = rules - set(RULES)
        if unknown:
            print(f"unknown rule ids: {sorted(unknown)} "
                  f"(known: {sorted(RULES)})", file=sys.stderr)
            return 2

    dot = args.lock_graph
    if dot is None and not args.paths:
        dot = root / DEFAULT_DOT
    findings = run_lint(repo_root=root, paths=args.paths or None,
                        rules=rules, lock_graph_out=dot)

    if args.as_json:
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        n_files = "repo" if not args.paths else f"{len(args.paths)} path(s)"
        print(f"hvd-lint: {len(findings)} finding(s) over {n_files}"
              + (f"; lock graph -> {dot}" if dot else ""))
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
