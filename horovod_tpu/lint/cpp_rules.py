"""C++ prong: engine-concurrency rules over ``engine/src`` (HVL101–103).

No compiler needed — a pattern scan plus a lightweight brace-tracking
parse is enough for the three contracts the engine's threading model
rests on:

- HVL101 — every timed condition-variable wait must go through
  ``CvWaitFor`` (common.h). gcc-10's libtsan cannot model
  ``pthread_cond_clockwait``, so a raw ``wait_for`` turns `make tsan`
  into a wall of bogus double-lock reports (the PR-4 rule, previously
  enforced only by reviewer memory).
- HVL102 — a static lock-order graph: within each scanned function,
  acquiring mutex B while holding mutex A adds edge A→B; a cycle in the
  union graph is a deadlock hazard. The graph is emitted as graphviz dot
  (``--lock-graph``) for review. Mutex identity is file-scoped (textual
  member/global name within one translation unit); the parse is
  intra-procedural, so call-chain inversions are out of scope — the TSan
  build covers those dynamically.
- HVL103 — atomics discipline: hot-path counters (MetricsStore, flight
  recorder) must pass ``memory_order_relaxed`` explicitly (a bare
  ``fetch_add`` is seq_cst — a silent hot-path regression), and fields
  whose names mark them as cross-thread lifecycle flags
  (shutdown/abort/stop/healthy...) must be ``std::atomic``.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from horovod_tpu.lint.base import Reporter

# -- HVL101: raw timed cv waits ----------------------------------------

_RAW_WAIT_RE = re.compile(
    r"\.\s*wait_for\s*\(|\.\s*wait_until\s*\(|pthread_cond_clockwait")


def check_raw_cv_wait(rep: Reporter, path: Path):
    fr = rep.scan_file(path)
    for i, line in enumerate(fr.lines, start=1):
        code = line.split("//", 1)[0]
        if _RAW_WAIT_RE.search(code):
            fr.add(
                "HVL101", i,
                "raw timed cv wait — use CvWaitFor (common.h): gcc-10 "
                "libtsan does not model pthread_cond_clockwait, so plain "
                "wait_for/wait_until poisons `make tsan` with bogus "
                "double-lock reports")


# -- HVL102: static lock-order graph -----------------------------------

_GUARD_RE = re.compile(
    r"std::(?P<kind>lock_guard|unique_lock|scoped_lock)\s*(?:<[^<>]*>)?\s+"
    r"(?P<var>\w+)\s*[({](?P<args>[^;]*?)[)}]\s*;")
_UNLOCK_RE = re.compile(r"\b(?P<var>\w+)\s*\.\s*unlock\s*\(\s*\)")


def _norm_mutex(expr: str) -> str:
    expr = expr.strip()
    expr = re.sub(r"^this\s*->\s*", "", expr)
    expr = re.sub(r"\s+", "", expr)
    return expr


class LockGraph:
    """Union lock-order graph over all scanned translation units."""

    def __init__(self):
        # edge (a, b) -> first acquisition site "file:line"
        self.edges: Dict[Tuple[str, str], str] = {}
        self.nodes: set = set()

    def add_edge(self, held: str, acquired: str, site: str):
        self.nodes.update((held, acquired))
        self.edges.setdefault((held, acquired), site)

    def cycles(self) -> List[List[str]]:
        """Elementary cycles found by DFS (enough to answer "any?" and
        name one per strongly-connected loop)."""
        adj: Dict[str, List[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        found: List[List[str]] = []
        seen_cycles = set()

        def dfs(node, stack, on_stack):
            for nxt in adj.get(node, ()):
                if nxt in on_stack:
                    cyc = stack[stack.index(nxt):] + [nxt]
                    key = frozenset(cyc)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        found.append(cyc)
                else:
                    stack.append(nxt)
                    on_stack.add(nxt)
                    dfs(nxt, stack, on_stack)
                    on_stack.discard(nxt)
                    stack.pop()

        for start in sorted(self.nodes):
            dfs(start, [start], {start})
        return found

    def to_dot(self) -> str:
        lines = ["digraph lock_order {",
                 '  rankdir=LR; node [shape=box, fontname="monospace"];',
                 "  // nodes = mutexes (file-scoped); edge A->B = B "
                 "acquired while A held, labeled with the site.",
                 "  // no edges means no nested locking anywhere — the "
                 "engine's preferred state."]
        cycle_edges = set()
        for cyc in self.cycles():
            for a, b in zip(cyc, cyc[1:]):
                cycle_edges.add((a, b))
        for node in sorted(self.nodes):
            lines.append(f'  "{node}";')
        for (a, b), site in sorted(self.edges.items()):
            style = ' color=red penwidth=2' if (a, b) in cycle_edges else ""
            lines.append(f'  "{a}" -> "{b}" [label="{site}"{style}];')
        lines.append("}")
        return "\n".join(lines) + "\n"


def scan_lock_orders(rep: Reporter, path: Path, graph: LockGraph):
    """Track RAII guard scopes by brace depth; each acquisition while
    other guards are live adds held→acquired edges."""
    fr = rep.scan_file(path)
    fname = path.name
    rel = rep._rel(path)
    depth = 0
    # live guards: list of (depth_at_acquisition, guard_var, mutex_node)
    live: List[Tuple[int, str, str]] = []
    for i, raw in enumerate(fr.lines, start=1):
        code = raw.split("//", 1)[0]
        # Process braces, guard declarations, and explicit unlocks in
        # source order: a guard lives at the brace depth of its
        # declaration *position* and dies when depth drops below it —
        # an unrelated inner block closing must not release it.
        events = sorted(
            [(j, "brace", ch) for j, ch in enumerate(code) if ch in "{}"]
            + [(m.start(), "guard", m) for m in _GUARD_RE.finditer(code)]
            + [(m.start(), "unlock", m) for m in _UNLOCK_RE.finditer(code)],
            key=lambda e: e[0])
        for _, kind, ev in events:
            if kind == "brace":
                if ev == "{":
                    depth += 1
                else:
                    depth -= 1
                    live = [g for g in live if g[0] <= depth]
                continue
            if kind == "unlock":
                var = ev.group("var")
                live = [g for g in live if g[1] != var]
                continue
            args = ev.group("args")
            # scoped_lock may take several mutexes; the others take
            # (mutex[, tag]) — the first argument is always the mutex,
            # std::defer_lock-style tags never contain '('.
            first = args.split(",")[0]
            mutexes = [first] if ev.group("kind") != "scoped_lock" \
                else args.split(",")
            for mx in mutexes:
                mx = _norm_mutex(mx)
                if not mx or mx in ("std::defer_lock", "std::adopt_lock",
                                    "std::try_to_lock"):
                    continue
                node = f"{fname}:{mx}"
                site = f"{rel}:{i}"
                for _, _, held in live:
                    if held == node:
                        fr.add(
                            "HVL102", i,
                            f"mutex `{mx}` acquired while already held "
                            "in the same scope chain — self-deadlock on "
                            "a non-recursive mutex")
                    else:
                        graph.add_edge(held, node, site)
                graph.nodes.add(node)
                live.append((depth, ev.group("var"), node))


def check_lock_order(rep: Reporter, paths: Sequence[Path],
                     dot_out: Path | None = None) -> LockGraph:
    graph = LockGraph()
    for p in paths:
        scan_lock_orders(rep, p, graph)
    for cyc in graph.cycles():
        sites = " -> ".join(cyc)
        path, line = cyc[0].split(":", 1)[0], 1
        edge_site = graph.edges.get((cyc[0], cyc[1]))
        if edge_site:
            path, _, ln = edge_site.rpartition(":")
            line = int(ln or 1)
        rep.add_repo_finding(
            "HVL102", Path(path), line,
            f"lock-order cycle (deadlock hazard): {sites} — two threads "
            "taking these mutexes in opposite orders can deadlock; "
            "impose a single acquisition order or collapse the locks")
    if dot_out is not None:
        dot_out.parent.mkdir(parents=True, exist_ok=True)
        dot_out.write_text(graph.to_dot())
    return graph


# -- HVL103: atomics discipline ----------------------------------------

# hot-path files where a bare fetch_add (seq_cst) is a perf regression
HOT_PATH_FILES = ("metrics.h", "metrics.cc",
                  "flight_recorder.h", "flight_recorder.cc")

_FETCH_ADD_RE = re.compile(r"\.\s*fetch_(?:add|sub)\s*\(")
_FLAG_FIELD_RE = re.compile(
    r"^\s*(?:volatile\s+)?(?:bool|u?int(?:32|64)?_t|int|size_t)\s+"
    r"(?P<name>\w*(?:shutdown|abort|stop|running|healthy|quit|"
    r"terminat)\w*_)\s*(?:=[^;]*)?;")


def check_atomics(rep: Reporter, path: Path):
    fr = rep.scan_file(path)
    hot = path.name in HOT_PATH_FILES
    for i, raw in enumerate(fr.lines, start=1):
        code = raw.split("//", 1)[0]
        # the memory_order argument may sit on a continuation line: join
        # from the call through the end of ITS statement (first ';'),
        # not beyond — the next statement's ordering must not mask this one
        m = _FETCH_ADD_RE.search(code)
        stmt = code[m.start():] if m else ""
        j = i
        while m and ";" not in stmt and j < len(fr.lines):
            stmt += " " + fr.lines[j].split("//", 1)[0]
            j += 1
        stmt = stmt.split(";", 1)[0]
        if hot and m and "memory_order_relaxed" not in stmt:
            fr.add(
                "HVL103", i,
                "hot-path counter increment without an explicit "
                "memory_order_relaxed — a bare fetch_add is seq_cst and "
                "puts a full fence on the per-collective fast path")
        if path.suffix == ".h":
            m = _FLAG_FIELD_RE.match(code)
            if m and "atomic" not in code:
                fr.add(
                    "HVL103", i,
                    f"`{m.group('name')}` looks like a cross-thread "
                    "lifecycle flag (background loop writes, API thread "
                    "reads) but is not std::atomic — a plain field is a "
                    "data race; wrap it or rename it if it is "
                    "mutex-guarded")
