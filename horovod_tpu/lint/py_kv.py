"""KV-key discipline rules (HVL007-HVL008).

Every rendezvous-KV key family is declared once in
``horovod_tpu/common/kv_keys.py`` (the env-registry pattern applied to
the KV namespace); these rules enforce the two sides of that contract:

- HVL007 — KV keys must be built through the typed builders, never as
  raw strings. Flagged: f-strings whose literal head is a registered
  family prefix, plain string literals starting with one (concatenation
  counts), and singleton key names (``"generation"``, ``"notify"``, ...)
  passed directly to a KV accessor. Docstrings are exempt (patterns are
  documentation), as is ``kv_keys.py`` itself.
- HVL008 — driver-originated KV mutations must claim the control epoch.
  In any module that owns a ``KVServer`` (the driver side), every
  ``put_json``/``delete``/``delete_prefix`` call must pass ``epoch=`` —
  an epoch-less driver write is invisible to the split-brain fencing
  that PR 10 built and the conformance checker replays.
"""

from __future__ import annotations

import ast
from pathlib import Path

from horovod_tpu.common.kv_keys import singleton_names, slash_prefixes
from horovod_tpu.lint.base import Reporter

# KV accessor spellings whose first argument is a key: the KV
# client/server methods, the router's local-getter convention, and the
# driver's publish/_publish wrappers (every driver command write goes
# through those — leaving them out would exempt the most
# protocol-critical keys from the rule)
_KV_ACCESSORS = {"put_json", "get_json", "kv_get_json", "kv_put_json",
                 "delete", "delete_prefix", "keys", "publish", "_publish"}
_MUTATORS = {"put_json", "delete", "delete_prefix"}


def _docstring_ids(tree: ast.AST) -> set:
    """id()s of Constant nodes that are docstrings."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) and \
                    isinstance(body[0].value, ast.Constant) and \
                    isinstance(body[0].value.value, str):
                out.add(id(body[0].value))
    return out


def _prefix_hit(text: str, prefixes) -> str | None:
    for p in prefixes:
        if text.startswith(p):
            return p
    return None


def check_python_kv_keys(rep: Reporter, path: Path):
    """HVL007 for one Python file."""
    if path.name == "kv_keys.py":
        return  # the registry builds its own keys, by definition
    fr = rep.scan_file(path)
    try:
        tree = ast.parse(fr.text, filename=str(path))
    except SyntaxError:
        return  # the collectives checker already reports parse failures
    prefixes = tuple(slash_prefixes())
    singles = singleton_names()
    skip = _docstring_ids(tree)
    # constituents of f-strings are flagged once, as the f-string
    for node in ast.walk(tree):
        if isinstance(node, ast.JoinedStr):
            skip.update(id(v) for v in node.values)

    def flag(line: int, key_text: str, how: str):
        fr.add(
            "HVL007", line,
            f"raw KV key construction ({how}: `{key_text}`) — build the "
            "key through horovod_tpu.common.kv_keys so the namespace "
            "stays typed and the protocol specs/conformance checker see "
            "the same prefixes the runtime uses")

    for node in ast.walk(tree):
        if isinstance(node, ast.JoinedStr) and node.values and \
                isinstance(node.values[0], ast.Constant):
            head = str(node.values[0].value)
            p = _prefix_hit(head, prefixes)
            if p is not None:
                flag(node.lineno, head + "...", "f-string")
        elif isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and id(node) not in skip:
            p = _prefix_hit(node.value, prefixes)
            if p is not None:
                flag(node.lineno, node.value, "string literal")
        elif isinstance(node, ast.Call):
            f = node.func
            fname = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if fname in _KV_ACCESSORS and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    node.args[0].value in singles:
                flag(node.lineno, node.args[0].value,
                     f"singleton key passed to {fname}()")


def check_python_kv_epochs(rep: Reporter, path: Path):
    """HVL008 for one Python file: only files that instantiate a
    ``KVServer`` are in scope (the driver side owns the epoch; workers'
    KVClient writes are epoch-less by design)."""
    if path.name == "http_kv.py":
        return  # the KV implementation itself
    fr = rep.scan_file(path)
    try:
        tree = ast.parse(fr.text, filename=str(path))
    except SyntaxError:
        return
    owns_server = any(
        isinstance(node, ast.Call) and (
            (isinstance(node.func, ast.Name) and
             node.func.id == "KVServer") or
            (isinstance(node.func, ast.Attribute) and
             node.func.attr == "KVServer"))
        for node in ast.walk(tree))
    if not owns_server:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr in _MUTATORS):
            continue
        if any(kw.arg == "epoch" for kw in node.keywords):
            continue
        fr.add(
            "HVL008", node.lineno,
            f"driver-originated KV write (`{f.attr}`) without an epoch "
            "claim — pass `epoch=` so the KV can fence a stale driver "
            "and the WAL records the claim for conformance replay "
            "(runner/http_kv.py fencing, PR 10)")
