"""``hvd-lint``: static analysis for the collective/engine contracts.

Two prongs (see docs/DESIGN.md "Static analysis & correctness tooling"):

- **Python** (stdlib ``ast``): rank-divergent collectives (HVL001),
  collective-order divergence (HVL002), swallowed aborts (HVL003), env
  discipline + typo detection + docs sync (HVL004–006).
- **C++** (pattern + lightweight parse over ``engine/src``): raw timed
  cv waits outside CvWaitFor (HVL101), static lock-order graph with
  cycle detection + dot emission (HVL102), atomics audit (HVL103).

Run ``hvd-lint`` / ``make lint`` / ``python -m horovod_tpu.lint``;
``tests/test_lint.py`` keeps the repository itself at zero findings.
"""

from horovod_tpu.lint.base import RULES, Finding  # noqa: F401
from horovod_tpu.lint.cli import main, run_lint  # noqa: F401
