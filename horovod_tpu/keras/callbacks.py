"""Keras callbacks (reference: horovod/_keras/callbacks.py:22-192 and the
thin keras-facing wrappers in horovod/keras/callbacks.py).

Keras-3 native: learning-rate access goes through
``model.optimizer.learning_rate`` (a variable) rather than the K.get_value
backend shims the reference needed for tf1/tf2 duality.
"""

from __future__ import annotations

import time
from typing import Optional

import keras
import numpy as np

from horovod_tpu.common import basics
from horovod_tpu.common import eager as _eager

# Log keys that must NOT be cross-rank averaged: the learning rate is a
# schedule output identical on every rank (averaging a per-rank-perturbed
# lr would silently corrupt LR-schedule callbacks that read it back).
_NON_AVERAGED_KEYS = frozenset({"lr", "learning_rate"})


def _averageable_keys(logs: dict) -> list:
    """Sorted log keys that should be cross-rank averaged: numeric scalars
    only (``np.isscalar`` alone also passes strings, which the old code
    would crash on), excluding lr-style schedule outputs and booleans."""
    keys = []
    for k, v in logs.items():
        if k in _NON_AVERAGED_KEYS or k.endswith("_lr") or \
                k.startswith("lr_"):
            continue
        if isinstance(v, bool) or isinstance(v, str):
            continue
        if isinstance(v, (int, float, np.integer, np.floating)):
            keys.append(k)
        elif getattr(v, "ndim", None) == 0 and \
                np.issubdtype(np.asarray(v).dtype, np.number):
            keys.append(k)
    return sorted(keys)


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast model + optimizer state from root after variables exist
    (reference: _keras/callbacks.py:22-47 — runs on the first batch end so
    lazily-built variables are included)."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        from horovod_tpu.tensorflow.functions import broadcast_model
        broadcast_model(self.model, self.root_rank,
                        optimizer=getattr(self.model, "optimizer", None))
        self.broadcast_done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch metrics over ranks before other callbacks (checkpoint,
    early stopping, lr schedules) read them (reference:
    _keras/callbacks.py:48-88).

    All averageable entries travel as ONE grouped vector through the same
    engine allreduce path every rank takes (a per-key loop could interleave
    with other collectives differently per rank); non-numeric entries and
    lr-style schedule outputs are passed through untouched."""

    def on_epoch_end(self, epoch, logs=None):
        if logs is None or basics.size() == 1:
            return
        keys = _averageable_keys(logs)
        if not keys:
            return
        vals = np.asarray([float(logs[k]) for k in keys], np.float64)
        avg = _eager.synchronize(_eager.allreduce_async(
            vals, op=_eager.Average, name=f"metric_avg.e{epoch}"))
        for k, v in zip(keys, np.asarray(avg)):
            logs[k] = float(v)


class MetricsCallback(keras.callbacks.Callback):
    """Feed per-batch step durations and epoch metrics into the process
    metrics registry (horovod_tpu.metrics) — served by the Prometheus
    exporter when ``HOROVOD_METRICS_PORT`` is set, and consumed by the
    elastic driver's straggler detection via the shared
    ``hvd_frontend_step_seconds`` histogram."""

    def __init__(self, registry=None):
        super().__init__()
        from horovod_tpu import metrics as _metrics
        self._registry = registry if registry is not None \
            else _metrics.get_registry()
        self._hist = self._registry.histogram(_metrics.STEP_SECONDS,
                                              framework="keras")
        self._steps = self._registry.counter(_metrics.STEPS_TOTAL,
                                             framework="keras")
        self._epochs = self._registry.counter(
            "hvd_frontend_epochs_total", framework="keras")
        self._t0 = None
        # step attributor (engine STEP marks + anomaly detection) — only on
        # the default registry; a test-supplied registry stays isolated
        self._attr = _metrics._get_attributor() if registry is None else None
        self._sid = 0

    def on_train_batch_begin(self, batch, logs=None):
        if self._attr is not None:
            self._sid = self._attr.next_step()
            self._attr.step_begin(self._sid)
        self._t0 = time.perf_counter()

    def on_train_batch_end(self, batch, logs=None):
        if self._t0 is not None:
            dt = time.perf_counter() - self._t0
            self._hist.observe(dt)
            self._t0 = None
            if self._attr is not None:
                self._attr.step_end(self._sid, dt)
        self._steps.inc()

    def on_epoch_end(self, epoch, logs=None):
        self._epochs.inc()
        for k in _averageable_keys(logs or {}):
            self._registry.gauge("hvd_frontend_epoch_metric",
                                 framework="keras",
                                 metric=k).set(float(logs[k]))


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Multiply the initial lr by ``multiplier(epoch)`` over
    [start_epoch, end_epoch) (reference: _keras/callbacks.py:89-171)."""

    def __init__(self, initial_lr: float, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 momentum_correction: bool = True, steps_per_epoch=None):
        super().__init__()
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        self._restore_momentum = None
        if not callable(multiplier):
            self.multiplier = lambda epoch: multiplier
            self.constant_multiplier = True
        else:
            self.multiplier = multiplier
            self.constant_multiplier = False

    def _in_range(self) -> bool:
        return self.current_epoch >= self.start_epoch and \
            (self.end_epoch is None or self.current_epoch < self.end_epoch)

    def _assign_lr(self, epoch_frac: float):
        lr = self.initial_lr * self.multiplier(epoch_frac)
        self.model.optimizer.learning_rate.assign(lr)
        return lr

    def _adjust_momentum(self, restore: bool = False):
        # momentum correction: scale momentum so velocity stays consistent
        # across an lr jump (reference: _keras/callbacks.py:140-160)
        opt = self.model.optimizer
        m = getattr(opt, "momentum", None)
        if m is None or self.constant_multiplier:
            return
        if restore and self._restore_momentum is not None:
            val = self._restore_momentum
            self._restore_momentum = None
        elif not restore:
            self._restore_momentum = float(
                m.numpy() if hasattr(m, "numpy") else m)
            lr0 = self.initial_lr * self.multiplier(
                max(self.current_epoch - 1, self.start_epoch))
            lr1 = self.initial_lr * self.multiplier(self.current_epoch)
            val = self._restore_momentum * (lr1 / max(lr0, 1e-12))
        else:
            return
        if hasattr(m, "assign"):
            m.assign(val)
        else:
            opt.momentum = val

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if not self._in_range():
            return
        if self.staircase:
            if self.momentum_correction:
                self._adjust_momentum()
            self._assign_lr(epoch)

    def on_batch_begin(self, batch, logs=None):
        if self.staircase or not self._in_range():
            return
        if self.steps_per_epoch is None:
            raise ValueError(
                "steps_per_epoch is required for non-staircase schedules")
        self._assign_lr(self.current_epoch + batch / self.steps_per_epoch)

    def on_epoch_end(self, epoch, logs=None):
        if self.momentum_correction and self.staircase and self._in_range():
            self._adjust_momentum(restore=True)
        if logs is not None:
            lr = self.model.optimizer.learning_rate
            logs["lr"] = float(lr.numpy() if hasattr(lr, "numpy") else lr)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Linear warmup from lr to lr*size over warmup_epochs (reference:
    _keras/callbacks.py:172-192 — the gradual-warmup recipe of the
    large-minibatch paper, docs/benchmarks analog)."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 momentum_correction: bool = True, steps_per_epoch=None,
                 verbose: int = 0):
        self.verbose = verbose
        world = basics.size() if basics.is_initialized() else 1

        def multiplier(epoch):
            # epoch 0 -> 1/size ... warmup end -> 1.0, in units of the
            # post-warmup (already size-scaled) initial_lr
            if warmup_epochs <= 0:
                return 1.0
            frac = min(epoch / float(warmup_epochs), 1.0)
            return (1.0 / world) * (1 + frac * (world - 1))
        super().__init__(initial_lr=initial_lr, multiplier=multiplier,
                         start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)

    def on_epoch_begin(self, epoch, logs=None):
        super().on_epoch_begin(epoch, logs)
        # warmup over: pin the exact target lr (batch-fraction assignments
        # end one fractional step short of it)
        if epoch >= self.end_epoch:
            self.model.optimizer.learning_rate.assign(self.initial_lr)

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if self.verbose and self.current_epoch == self.end_epoch - 1 and \
                basics.rank() == 0:
            print("Epoch %d: finished gradual learning rate warmup to %g." %
                  (epoch + 1, self.initial_lr))
