"""Keras frontend (reference: horovod/keras/__init__.py — the standalone
keras entry point; same surface as horovod_tpu.tensorflow.keras)."""

from horovod_tpu.tensorflow import (  # noqa: F401
    Adasum, Average, Compression, Max, Min, Op, Product, Sum,
    DistributedOptimizer, DistributedGradientTape,
    allgather, allgather_object, allreduce, alltoall, barrier, broadcast,
    broadcast_model, broadcast_object, broadcast_variables,
    grouped_allreduce, init, is_initialized, join, local_rank, local_size,
    metric_average, rank, shutdown, size,
)
from horovod_tpu.keras import callbacks  # noqa: F401


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none, **distopt_kwargs):
    """Load a saved Keras model with its optimizer re-wrapped in a
    DistributedOptimizer, so saved optimizer state (iterations, slot
    variables) is picked up for continued distributed training
    (reference: keras/__init__.py:147-181 + _keras/__init__.py:165-181).

    All built-in ``keras.optimizers`` classes are remapped automatically;
    pass ``custom_optimizers`` (a list of Optimizer subclasses) for your
    own, or ``custom_objects`` for any other custom layers/classes.
    Extra ``distopt_kwargs`` (op=, backward_passes_per_step=, ...) flow to
    DistributedOptimizer.
    """
    import keras

    def wrap(cls):
        # A dynamic subclass whose from_config returns the wrapped
        # optimizer: keras deserializes into it, then loads the saved
        # optimizer variables into the wrapped instance.
        def from_config(klass, config, custom_objects=None):
            del klass, custom_objects
            base = cls.from_config(config)
            return DistributedOptimizer(base, compression=compression,
                                        **distopt_kwargs)

        return type(cls.__name__, (cls,),
                    {"from_config": classmethod(from_config)})

    base_cls = keras.optimizers.Optimizer
    horovod_objects = {}
    for name in dir(keras.optimizers):
        cls = getattr(keras.optimizers, name)
        if (isinstance(cls, type) and issubclass(cls, base_cls)
                and cls is not base_cls):
            wrapped = wrap(cls)
            horovod_objects[cls.__name__] = wrapped
            # legacy h5 saves used lowercase class names (reference:
            # _keras/__init__.py:167)
            horovod_objects[cls.__name__.lower()] = wrapped
    if custom_optimizers is not None:
        horovod_objects.update(
            {cls.__name__: wrap(cls) for cls in custom_optimizers})
    if custom_objects is not None:
        horovod_objects.update(custom_objects)
    return keras.models.load_model(filepath, custom_objects=horovod_objects)
