"""Keras frontend (reference: horovod/keras/__init__.py — the standalone
keras entry point; same surface as horovod_tpu.tensorflow.keras)."""

from horovod_tpu.tensorflow import (  # noqa: F401
    Adasum, Average, Compression, Max, Min, Op, Product, Sum,
    DistributedOptimizer, DistributedGradientTape,
    allgather, allgather_object, allreduce, alltoall, barrier, broadcast,
    broadcast_model, broadcast_object, broadcast_variables,
    grouped_allreduce, init, is_initialized, join, local_rank, local_size,
    metric_average, rank, shutdown, size,
)
from horovod_tpu.keras import callbacks  # noqa: F401
