"""Distributed optimizer wrappers for torch.

Reference analog: horovod/torch/optimizer.py — ``_DistributedOptimizer``
fires ``allreduce_async_`` from per-parameter gradient-accumulator hooks as
soon as each gradient is ready (:110-198), and ``step()`` → ``synchronize()``
waits the handles and decompresses (:200-260); ``backward_passes_per_step``
delay counters; ``_DistributedAdasumOptimizer`` (:270-440) applies the LR
*before* reduction and Adasum-combines parameter deltas.

The hook mechanism is torch-2.x native (`register_post_accumulate_grad_hook`)
instead of the reference's grad_fn accumulator introspection; the overlap
property is the same — reductions for early layers start while later layers
are still in backward.
"""

from __future__ import annotations

import contextlib
import time as _time
from typing import Iterator, Optional

import torch

from horovod_tpu.common import basics
from horovod_tpu.torch import mpi_ops
from horovod_tpu.torch.compression import Compression

# Step-timer instruments, resolved once (registry-lock + label-key cost is
# per process, not per optimizer step).
_step_instruments = None


def _record_torch_step(seconds: float):
    global _step_instruments
    if _step_instruments is None:
        from horovod_tpu import metrics as _metrics
        reg = _metrics.get_registry()
        _step_instruments = (
            reg.histogram(_metrics.STEP_SECONDS, framework="torch"),
            reg.counter(_metrics.STEPS_TOTAL, framework="torch"),
            _metrics._get_attributor())
    _step_instruments[0].observe(seconds)
    _step_instruments[1].inc()
    if _step_instruments[2] is not None:
        # optimizer.step() times after the fact — anomaly detection only,
        # no engine STEP marks to bracket with
        _step_instruments[2].observe(seconds)


class _DistributedOptimizer(torch.optim.Optimizer):
    """Mixin applied over the user's optimizer class (the reference's
    dynamic-subclass pattern, optimizer.py:443-508) — isinstance checks and
    LR schedulers keep working against the original class."""

    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step, op,
                 gradient_predivide_factor, groups, sharded=False):
        super(self.__class__, self).__init__(params)
        self._compression = compression or Compression.none
        self._bpps = int(backward_passes_per_step)
        if self._bpps < 1:
            raise ValueError("backward_passes_per_step must be >= 1")
        self._op = op
        self._gradient_predivide_factor = gradient_predivide_factor
        self._groups = groups
        self._sharded = bool(sharded)
        self._owner = {}
        if named_parameters is not None:
            named_parameters = list(named_parameters)
            self._param_names = {id(p): name for name, p in named_parameters}
        else:
            self._param_names = {
                id(p): f"param.{gi}.{pi}"
                for gi, g in enumerate(self.param_groups)
                for pi, p in enumerate(g["params"])}
        dups = _find_duplicates(self._param_names.values())
        if dups:
            raise ValueError(
                f"duplicate parameter names: {sorted(dups)} — collective "
                "tensor names must be unique across the model")
        self._handles = {}       # param -> (handle, compression ctx)
        self._delay = {}         # param -> remaining backward passes
        self._requires_update = set()
        self._synchronized = False
        self._should_synchronize = True
        self._hook_handles = []
        if basics._context().engine is not None or basics._context().size > 1:
            self._register_hooks()

    # -- hooks ---------------------------------------------------------------

    def _register_hooks(self):
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._requires_update.add(p)
                    self._delay[p] = self._bpps
                    self._hook_handles.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook(p)))

    def _make_hook(self, p):
        def hook(*ignore):
            if p in self._handles and self._handles[p][0] is not None:
                if self._delay[p] <= 0:
                    raise AssertionError(
                        "Gradients were computed more than "
                        "backward_passes_per_step times before call to "
                        "step(). Increase backward_passes_per_step.")
            self._delay[p] -= 1
            if self._delay[p] == 0:
                self._handles[p] = self._allreduce_grad_async(p)
        return hook

    def _allreduce_grad_async(self, p):
        name = self._param_names.get(id(p)) or f"param.{id(p)}"
        tensor = p.grad
        if self._op is mpi_ops.Average \
                and self._gradient_predivide_factor != 1.0:
            prescale = 1.0 / self._gradient_predivide_factor
            postscale = self._gradient_predivide_factor \
                / basics._context().size
            tensor_compressed, ctx = self._compression.compress(tensor)
            handle = mpi_ops.allreduce_async_(
                tensor_compressed, name=f"allreduce.{name}", op=mpi_ops.Sum,
                prescale_factor=prescale, postscale_factor=postscale)
        else:
            tensor_compressed, ctx = self._compression.compress(tensor)
            handle = mpi_ops.allreduce_async_(
                tensor_compressed, name=f"allreduce.{name}", op=self._op)
        return handle, (tensor_compressed, ctx)

    # -- synchronize ---------------------------------------------------------

    def synchronize(self):
        """Wait outstanding gradient reductions and write reduced grads back
        (reference: optimizer.py:200-260)."""
        for p in self._requires_update:
            if p not in self._handles and p.grad is not None:
                # hook never fired (grads set manually, or step() called
                # mid-accumulation) — force the reduction, as the reference
                # synchronize() does (optimizer.py:200-232)
                self._handles[p] = self._allreduce_grad_async(p)
        for p, (handle, (tensor_compressed, ctx)) in self._handles.items():
            mpi_ops.synchronize(handle)
            self._delay[p] = self._bpps
            grad = self._compression.decompress(tensor_compressed, ctx)
            if grad.data_ptr() != p.grad.data_ptr():
                p.grad.copy_(grad.to(p.grad.dtype))
        self._handles.clear()
        self._synchronized = True

    @contextlib.contextmanager
    def skip_synchronize(self):
        """Advanced: user already called ``synchronize()`` manually
        (reference: optimizer.py skip_synchronize)."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    # -- ZeRO-1 weight-update sharding (eager analog of parallel/zero.py;
    # -- technique: Xu et al., arXiv:2004.13336) ------------------------------

    def _compute_owners(self):
        """Deterministic greedy partition of parameters across ranks:
        largest-first onto the least-loaded rank. Every rank computes the
        same assignment from the same param_groups order — no negotiation
        round needed."""
        size = max(basics._context().size, 1)
        loads = [0] * size
        ordered = [p for g in self.param_groups for p in g["params"]
                   if p.requires_grad]
        # stable: sort by (-numel, original position)
        for pos, p in sorted(enumerate(ordered),
                             key=lambda ip: (-ip[1].numel(), ip[0])):
            owner = min(range(size), key=lambda r: (loads[r], r))
            loads[owner] += p.numel()
            self._owner[p] = owner

    def _sharded_step(self, closure):
        """Owner-only inner step + parameter broadcast: the optimizer
        materializes state (Adam moments, ...) ONLY for the ~1/N of
        parameters this rank owns, and performs ~1/N of the update FLOPs.
        Grads still arrive via allreduce (the eager engine's reduction
        primitive); the saving here is state memory + update compute, the
        redundancy arXiv:2004.13336 targets."""
        rank = basics._context().rank
        stashed = []
        for p in list(self._owner):
            if self._owner[p] != rank and p.grad is not None:
                stashed.append((p, p.grad))
                p.grad = None  # torch optimizers skip grad-None params
        loss = super(self.__class__, self).step(closure)
        for p, grad in stashed:
            p.grad = grad  # restore: post-step grad consumers see all grads
        handles = []
        for p, owner in self._owner.items():
            name = self._param_names.get(id(p)) or f"param.{id(p)}"
            handles.append(mpi_ops.broadcast_async_(
                p.data, root_rank=owner, name=f"zero.param.{name}"))
        for h in handles:
            mpi_ops.synchronize(h)
        return loss

    def step(self, closure=None):
        # Step timer (metrics monitoring layer): covers grad synchronize +
        # the optimizer update — the torch analog of the jax train-step
        # wrapper, feeding the same hvd_frontend_step_seconds histogram the
        # elastic driver's straggler detection reads.
        t0 = _time.perf_counter()
        try:
            if self._should_synchronize:
                if self._synchronized:
                    import warnings
                    warnings.warn(
                        "optimizer.step() called without a preceding "
                        "backward; gradients were already synchronized")
                self.synchronize()
            self._synchronized = False
            if self._sharded and basics._context().size > 1:
                if not self._owner:
                    self._compute_owners()
                return self._sharded_step(closure)
            return super(self.__class__, self).step(closure)
        finally:
            _record_torch_step(_time.perf_counter() - t0)

    def zero_grad(self, set_to_none: bool = True):
        if self._handles:
            raise AssertionError(
                "zero_grad called with outstanding gradient reductions — "
                "call step() or synchronize() first")
        return super(self.__class__, self).zero_grad(set_to_none)


class _DistributedAdasumOptimizer(torch.optim.Optimizer):
    """Adasum applies the learning rate *before* reduction and combines
    parameter deltas scale-invariantly (reference: optimizer.py:270-440).

    step(): snapshot params → inner step on local grads → delta = new-old →
    Adasum-allreduce deltas → params = old + combined delta."""

    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step):
        super(self.__class__, self).__init__(params)
        self._compression = compression or Compression.none
        self._bpps = int(backward_passes_per_step)
        self._step_count = 0
        if named_parameters is not None:
            self._param_names = {id(p): name
                                 for name, p in list(named_parameters)}
        else:
            self._param_names = {}

    def step(self, closure=None):
        self._step_count += 1
        if self._bpps > 1 and (self._step_count % self._bpps) != 0:
            return None  # local accumulation continues
        starts = {}
        for group in self.param_groups:
            for p in group["params"]:
                if p.grad is not None:
                    starts[p] = p.detach().clone()
        loss = super(self.__class__, self).step(closure)
        handles = []
        for group in self.param_groups:
            for p in group["params"]:
                if p.grad is None:
                    continue
                delta = p.detach() - starts[p]
                name = self._param_names.get(id(p)) or f"param.{id(p)}"
                compressed, cctx = self._compression.compress(delta)
                h = mpi_ops.allreduce_async(
                    compressed, name=f"adasum.{name}", op=mpi_ops.Adasum)
                handles.append((p, h, cctx))
        for p, h, cctx in handles:
            combined = self._compression.decompress(mpi_ops.synchronize(h),
                                                    cctx)
            with torch.no_grad():
                p.copy_(starts[p] + combined.to(p.dtype))
        return loss


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters: Optional[Iterator] = None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op=mpi_ops.Average,
                         gradient_predivide_factor: float = 1.0,
                         groups=None,
                         sharded: bool = False) -> torch.optim.Optimizer:
    """Wrap a torch optimizer with hook-driven gradient allreduce
    (reference: horovod/torch/optimizer.py:443-508).

    ``sharded=True`` enables ZeRO-1-style weight-update sharding (the eager
    analog of ``horovod_tpu.parallel.zero``): parameters are partitioned
    across ranks, each rank runs the inner optimizer only on its ~1/N
    partition (so optimizer state is ~1/N per rank), and updated parameters
    are broadcast from their owners after ``step()``."""
    if gradient_predivide_factor != 1.0 and op is not mpi_ops.Average:
        raise ValueError(
            "gradient_predivide_factor not supported with op != Average")
    if op is mpi_ops.Adasum:
        if sharded:
            raise ValueError("sharded=True is incompatible with Adasum — "
                             "Adasum combines full parameter deltas")
        cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
                   dict(_DistributedAdasumOptimizer.__dict__))
        return cls(optimizer.param_groups, named_parameters, compression,
                   backward_passes_per_step)
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, op, gradient_predivide_factor,
               groups, sharded)


def _find_duplicates(names) -> set:
    seen, dups = set(), set()
    for n in names:
        (dups if n in seen else seen).add(n)
    return dups
