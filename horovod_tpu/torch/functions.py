"""Parameter/object broadcast helpers for torch models.

Reference analog: horovod/torch/functions.py — broadcast_parameters
(:29-112), broadcast_optimizer_state (:113-185), broadcast_object (:186-228),
allgather_object. The checkpoint-consistency primitives: after rank 0 loads
or initializes, every rank is synced before the first training collective.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Optional

import numpy as np
import torch

from horovod_tpu.common import basics
from horovod_tpu.torch import mpi_ops


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """In-place broadcast of a parameter collection from ``root_rank``
    (reference: functions.py:29-112). Accepts a ``state_dict()`` (name →
    tensor mapping) or an iterable of (name, tensor) — the
    ``model.named_parameters()`` pattern. Async-submits every entry then
    synchronizes, letting the engine fuse the transfers."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    handles = []
    for name, p in items:
        if p is None or not isinstance(p, torch.Tensor):
            continue
        t = p.data if isinstance(p, torch.nn.Parameter) else p
        handles.append(mpi_ops.broadcast_async_(
            t, root_rank, name=f"bcast_params.{name}"))
    for h in handles:
        mpi_ops.synchronize(h)


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0) -> None:
    """Broadcast optimizer state from root (reference: functions.py:113-185).

    Tensor state entries (momentum buffers, exp_avg, ...) broadcast in place
    as tensors; the structural remainder (step counts, param_group scalars)
    rides one pickled object broadcast and is load_state_dict'ed on non-root
    ranks so newly created state (e.g. before the first step on late ranks)
    materializes consistently."""
    if _single_process():
        return
    state_dict = optimizer.state_dict()
    # Structure first: ranks whose optimizer has not materialized state yet
    # (no step taken) adopt root's structure before tensor broadcasts.
    meta = {
        "param_groups": state_dict["param_groups"],
        "state_keys": {
            k: {sk: (tuple(sv.shape), str(sv.dtype))
                if isinstance(sv, torch.Tensor) else ("py", repr(type(sv)))
                for sk, sv in v.items()}
            for k, v in state_dict["state"].items()},
    }
    root_meta = broadcast_object(meta, root_rank, name="opt_state_meta")
    if basics._context().rank != root_rank:
        # Materialize missing tensor slots with the right shapes/dtypes.
        for k, slots in root_meta["state_keys"].items():
            st = state_dict["state"].setdefault(k, {})
            for sk, (shape, dtype) in slots.items():
                if shape == "py":
                    continue
                if sk not in st or not isinstance(st[sk], torch.Tensor):
                    st[sk] = torch.zeros(
                        shape, dtype=getattr(torch, dtype.split(".")[-1]))
    handles = []
    scalars = {}
    for k, v in sorted(state_dict["state"].items()):
        for sk, sv in sorted(v.items()):
            if isinstance(sv, torch.Tensor):
                handles.append(mpi_ops.broadcast_async_(
                    sv, root_rank, name=f"bcast_opt.{k}.{sk}"))
            else:
                scalars[(k, sk)] = sv
    for h in handles:
        mpi_ops.synchronize(h)
    scalars = broadcast_object(scalars, root_rank, name="opt_state_scalars")
    if basics._context().rank != root_rank:
        for (k, sk), sv in scalars.items():
            state_dict["state"][k][sk] = sv
        state_dict["param_groups"] = root_meta["param_groups"]
        optimizer.load_state_dict(state_dict)


def broadcast_object(obj: Any, root_rank: int = 0,
                     name: Optional[str] = None) -> Any:
    """Pickle + broadcast an arbitrary python object (reference:
    functions.py:186-228: size broadcast, then payload)."""
    name = name or "broadcast_object"
    if _single_process():
        return obj
    if basics._context().rank == root_rank:
        buf = io.BytesIO()
        pickle.dump(obj, buf)
        payload = torch.from_numpy(
            np.frombuffer(buf.getvalue(), np.uint8).copy())
    else:
        payload = torch.zeros(0, dtype=torch.uint8)
    sz = torch.tensor([payload.numel()], dtype=torch.int64)
    sz = mpi_ops.synchronize(
        mpi_ops.broadcast_async(sz, root_rank, name=name + ".sz"))
    if basics._context().rank != root_rank:
        payload = torch.zeros(int(sz[0]), dtype=torch.uint8)
    data = mpi_ops.synchronize(
        mpi_ops.broadcast_async(payload, root_rank, name=name + ".data"))
    return pickle.loads(data.numpy().tobytes())


def allgather_object(obj: Any, name: Optional[str] = None) -> list:
    """Gather one python object per rank (reference: torch/functions.py
    allgather_object): pickled blobs ride the ragged allgather."""
    name = name or "allgather_object"
    if _single_process():
        return [obj]
    buf = io.BytesIO()
    pickle.dump(obj, buf)
    payload = torch.from_numpy(np.frombuffer(buf.getvalue(), np.uint8).copy())
    sizes = mpi_ops.synchronize(mpi_ops.allgather_async(
        torch.tensor([payload.numel()], dtype=torch.int64),
        name=name + ".sz"))
    data = mpi_ops.synchronize(
        mpi_ops.allgather_async(payload, name=name + ".data")).numpy()
    out = []
    off = 0
    for s in sizes.ravel().tolist():
        out.append(pickle.loads(data[off:off + int(s)].tobytes()))
        off += int(s)
    return out


def _single_process() -> bool:
    return basics._single_process()
