"""Gradient wire compression for the torch frontend.

Reference analog: horovod/torch/compression.py — ``Compression.none`` /
``Compression.fp16`` pairs of (compress, decompress) applied around the
allreduce wire transfer. A TPU-minded addition: ``Compression.bf16`` keeps
the fp32 exponent range (no overflow on large gradient norms), which is the
dtype the TPU data path prefers anyway.
"""

from __future__ import annotations

import torch


class Compressor:
    @staticmethod
    def compress(tensor: torch.Tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference: compression.py NoneCompressor)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Float tensors ride the wire as fp16 (reference: compression.py:46-66)."""

    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point:
            return tensor.type(torch.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.type(ctx) if ctx is not None else tensor


class BF16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point:
            return tensor.type(torch.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.type(ctx) if ctx is not None else tensor


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
