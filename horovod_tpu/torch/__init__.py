"""The PyTorch user frontend — analog of the reference's ``horovod.torch``
package (reference: horovod/torch/__init__.py).

torch here is a *frontend over the same engine* the JAX surface uses: eager
collectives stage through host numpy buffers, the C++ controller negotiates
and fuses across ranks, and the host data plane executes. A torch training
loop wrapped with ``DistributedOptimizer`` trains data-parallel across
processes exactly as the reference's does — while the TPU-resident compute
path stays available through ``horovod_tpu.jax``.
"""

from horovod_tpu.common.basics import (  # noqa: F401
    cross_rank, cross_size, init, is_initialized, local_rank, local_size,
    mpi_threads_supported, nccl_built, rank, shutdown, size,
    start_timeline, stop_timeline,
)
from horovod_tpu.torch.compression import Compression  # noqa: F401
from horovod_tpu.torch.mpi_ops import (  # noqa: F401
    Adasum, Average, Max, Min, Op, Product, Sum,
    allgather, allgather_async,
    allreduce, allreduce_, allreduce_async, allreduce_async_,
    alltoall, alltoall_async,
    barrier,
    broadcast, broadcast_, broadcast_async, broadcast_async_,
    grouped_allreduce, grouped_allreduce_, grouped_allreduce_async,
    grouped_allreduce_async_,
    join, poll, synchronize,
)
from horovod_tpu.torch.functions import (  # noqa: F401
    allgather_object, broadcast_object, broadcast_optimizer_state,
    broadcast_parameters,
)
from horovod_tpu.torch.optimizer import DistributedOptimizer  # noqa: F401
from horovod_tpu.torch.sync_batch_norm import SyncBatchNorm  # noqa: F401
from horovod_tpu.torch import elastic  # noqa: F401
