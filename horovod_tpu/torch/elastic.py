"""Elastic training state + mid-epoch sampler for torch.

Reference analog: horovod/torch/elastic/state.py (TorchState with model /
optimizer / sampler handlers) and horovod/torch/elastic/sampler.py
(ElasticSampler — processed-index tracking so a rank resize mid-epoch
resumes with every remaining sample processed exactly once).

The retry loop (``run``) and the commit/restore/check-host-updates machinery
are framework-neutral and shared with the JAX frontend
(horovod_tpu/jax/elastic.py).
"""

from __future__ import annotations

import copy
import math
from typing import Optional

import torch

from horovod_tpu.common import basics
from horovod_tpu.jax.elastic import State, run  # noqa: F401  (re-exported)
from horovod_tpu.torch import functions as torch_functions


class ElasticSampler(torch.utils.data.Sampler):
    """Distributed sampler that tracks processed indices for mid-epoch
    elastic resume (reference: torch/elastic/sampler.py).

    Usage: iterate batches; call ``record_batch(batch_idx, batch_size)``
    after each; on a resize, ``reset()`` (via TorchState.on_reset) reshuffles
    the *remaining* indices over the new world — already-processed samples
    are not replayed."""

    def __init__(self, dataset, shuffle: bool = True, seed: int = 0):
        self.dataset = dataset
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed_indices = set()
        self.num_samples = 0
        self.total_size = 0
        self.indices = []
        self.reset()

    def set_epoch(self, epoch: int):
        """New epoch: clear processed tracking, reshuffle (reference:
        sampler.py set_epoch)."""
        self.epoch = epoch
        self.processed_indices = set()
        self.reset()

    def record_batch(self, batch_idx: int, batch_size: int):
        """Mark one iterated batch as processed."""
        start = batch_idx * batch_size
        self.record_indices(self.indices[start:start + batch_size])

    def record_indices(self, indices):
        self.processed_indices.update(indices)

    def reset(self):
        ctx = basics._context()
        rank = ctx.rank if ctx.initialized else 0
        world = ctx.size if ctx.initialized else 1

        g = torch.Generator()
        g.manual_seed(self.seed + self.epoch)
        order = torch.randperm(len(self.dataset), generator=g).tolist() \
            if self.shuffle else list(range(len(self.dataset)))
        remaining = [i for i in order if i not in self.processed_indices]

        self.num_samples = int(math.ceil(len(remaining) / world)) \
            if remaining else 0
        self.total_size = self.num_samples * world
        # pad so every rank sees the same number of batches (standard
        # DistributedSampler contract; collectives stay in lockstep) —
        # repeating the remainder as many times as needed, since at an epoch
        # tail len(remaining) can be smaller than the pad itself
        if remaining:
            pad = self.total_size - len(remaining)
            if pad > 0:
                remaining = remaining + \
                    (remaining * math.ceil(pad / len(remaining)))[:pad]
        self.indices = remaining[rank:self.total_size:world]

    def __iter__(self):
        return iter(self.indices)

    def __len__(self):
        return self.num_samples

    def state_dict(self) -> dict:
        return {"epoch": self.epoch,
                "processed_indices": set(self.processed_indices)}

    def load_state_dict(self, state: dict):
        self.epoch = state["epoch"]
        self.processed_indices = set(state["processed_indices"])
        self.reset()


class TorchState(State):
    """Elastic state with torch-aware handlers (reference:
    torch/elastic/state.py:27-140): ``model``s sync via in-place parameter
    broadcast, ``optimizer``s via optimizer-state broadcast, samplers merge
    processed indices across the old world before re-partitioning."""

    def __init__(self, model: Optional[torch.nn.Module] = None,
                 optimizer: Optional[torch.optim.Optimizer] = None,
                 sampler: Optional[ElasticSampler] = None, **kwargs):
        self.model = model
        self.optimizer = optimizer
        self.sampler = sampler
        self._model_state = None
        self._optimizer_state = None
        self._sampler_state = None
        super().__init__(**kwargs)

    # -- commit/restore ------------------------------------------------------

    def commit_no_check(self):
        if self.model is not None:
            self._model_state = copy.deepcopy(self.model.state_dict())
        if self.optimizer is not None:
            self._optimizer_state = copy.deepcopy(
                self.optimizer.state_dict())
        if self.sampler is not None:
            self._sampler_state = self.sampler.state_dict()
        super().commit_no_check()

    def restore(self):
        if self.model is not None and self._model_state is not None:
            self.model.load_state_dict(self._model_state)
        if self.optimizer is not None and self._optimizer_state is not None:
            self.optimizer.load_state_dict(self._optimizer_state)
        if self.sampler is not None and self._sampler_state is not None:
            self.sampler.load_state_dict(self._sampler_state)
        super().restore()

    def sync(self):
        if basics._context().engine is not None:
            if self.model is not None:
                torch_functions.broadcast_parameters(
                    self.model.state_dict(), root_rank=0)
            if self.optimizer is not None:
                torch_functions.broadcast_optimizer_state(
                    self.optimizer, root_rank=0)
            if self.sampler is not None:
                # union of every rank's processed set — a departed rank's
                # progress came in via the last committed broadcast state;
                # surviving ranks merge so no sample is replayed
                merged = torch_functions.allgather_object(
                    self.sampler.processed_indices,
                    name="elastic_sampler_sync")
                union = set()
                for s in merged:
                    union |= s
                self.sampler.processed_indices = union
                self.sampler.reset()
        super().sync()

    def on_reset(self):
        if self.sampler is not None:
            self.sampler.reset()
        super().on_reset()
