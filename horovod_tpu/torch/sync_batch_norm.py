"""Cross-rank synchronized batch normalization for torch.

Reference analog: horovod/torch/sync_batch_norm.py — batch statistics
computed over the global batch (all ranks), used when per-rank batches are
too small for stable BN.

Design: instead of the reference's hand-derived backward (allgather of
mean/invstd + a custom autograd Function), the statistics are computed with
the *differentiable* eager allreduce (horovod_tpu.torch.mpi_ops.allreduce,
whose backward is the mirror allreduce) — autograd then produces exactly the
synchronized gradients, with no bespoke backward to keep in sync.
"""

from __future__ import annotations

import torch
from torch.nn.modules.batchnorm import _BatchNorm

from horovod_tpu.common import basics
from horovod_tpu.torch import mpi_ops


class SyncBatchNorm(_BatchNorm):
    """Drop-in BatchNorm that synchronizes statistics across ranks during
    training (reference: torch/sync_batch_norm.py SyncBatchNorm)."""

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 track_running_stats=True):
        super().__init__(num_features, eps, momentum, affine,
                         track_running_stats)

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D)")

    def forward(self, input):
        ctx = basics._context()
        world = ctx.size if ctx.initialized else 1
        if not self.training or world == 1:
            return super().forward(input)
        self._check_input_dim(input)

        # per-channel local sums over every dim but the channel dim (1)
        dims = [0] + list(range(2, input.dim()))
        local_count = input.numel() // input.shape[1]
        local_sum = input.sum(dim=dims)
        local_sqsum = (input * input).sum(dim=dims)

        counts = mpi_ops.synchronize(mpi_ops.allgather_async(
            torch.tensor([local_count], dtype=torch.int64)))
        total = int(counts.sum())
        mean = mpi_ops.allreduce(local_sum, op=mpi_ops.Sum) / total
        sqmean = mpi_ops.allreduce(local_sqsum, op=mpi_ops.Sum) / total
        var = sqmean - mean * mean

        if self.track_running_stats:
            with torch.no_grad():
                self.num_batches_tracked += 1
                # momentum=None means cumulative moving average, matching
                # the _BatchNorm contract
                m = self.momentum if self.momentum is not None \
                    else 1.0 / float(self.num_batches_tracked)
                unbiased = var * total / max(total - 1, 1)
                self.running_mean.mul_(1 - m).add_(mean.detach(), alpha=m)
                self.running_var.mul_(1 - m).add_(unbiased.detach(), alpha=m)

        shape = [1, -1] + [1] * (input.dim() - 2)
        out = (input - mean.reshape(shape)) \
            / torch.sqrt(var.reshape(shape) + self.eps)
        if self.affine:
            out = out * self.weight.reshape(shape) \
                + self.bias.reshape(shape)
        return out
