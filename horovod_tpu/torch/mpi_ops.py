"""PyTorch eager collective operations over the native coordination engine.

Reference analog: horovod/torch/mpi_ops.py (the full sync + ``_async`` +
in-place ``_``-suffixed surface, handles with ``synchronize``/``poll``,
autograd-aware sync ops) and horovod/torch/mpi_ops_v2.cc (the C++ adapter
whose role — tensor staging + handle management — is played here by the
framework-neutral executor in horovod_tpu/common/eager.py).

TPU-native design: torch is a *frontend*. Tensors are staged to host numpy
buffers (the reference's *CudaOnCPU pattern, torch/mpi_ops_v2.cc), the C++
engine negotiates + fuses across ranks, and the host data plane executes.
There is no torch C++ extension because there is nothing device-specific to
adapt — the TPU compute path lives in jit (horovod_tpu.jax); this surface
serves torch training loops, parameter broadcasts, and API parity.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np
import torch

from horovod_tpu.common import eager as _eager
from horovod_tpu.common.reduce_ops import (  # noqa: F401  (re-exported)
    Adasum, Average, Max, Min, Op, Product, Sum,
)

# ---------------------------------------------------------------------------
# torch <-> numpy staging (exact bit round-trips, incl. bf16/f16)


def _to_numpy(tensor: torch.Tensor) -> np.ndarray:
    t = tensor.detach().contiguous().cpu()
    if t.dtype == torch.bfloat16:
        import ml_dtypes
        return t.view(torch.int16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


def _from_numpy(arr: np.ndarray) -> torch.Tensor:
    import ml_dtypes
    if arr.dtype == ml_dtypes.bfloat16:
        return torch.from_numpy(arr.view(np.int16).copy()).view(torch.bfloat16)
    return torch.from_numpy(np.ascontiguousarray(arr))


# ---------------------------------------------------------------------------
# handle table (reference: HandleManager, torch/mpi_ops_v2.cc:441-477 —
# int handles so torch-side callers can poll/synchronize out of order)

_handle_lock = threading.Lock()
_next_handle = [0]
_handles: dict = {}  # int -> (eager handle, output torch tensor or None)


def _register(eager_handle, output: Optional[torch.Tensor]) -> int:
    with _handle_lock:
        h = _next_handle[0]
        _next_handle[0] += 1
        _handles[h] = (eager_handle, output)
    return h


def poll(handle: int) -> bool:
    """True once the async op has completed (reference: mpi_ops.py:807-822)."""
    with _handle_lock:
        entry = _handles.get(handle)
    if entry is None:
        raise ValueError(f"unknown handle {handle}")
    return _eager.poll(entry[0])


def synchronize(handle: int) -> Optional[torch.Tensor]:
    """Wait for an async op and return its output tensor (reference:
    mpi_ops.py:823-845). For in-place ops the input tensor is updated and
    returned."""
    return _synchronize_with_aux(handle)[0]


def _synchronize_with_aux(handle: int):
    """synchronize() plus the op's auxiliary outputs (alltoall recv_splits,
    allgather rank_sizes) that ride the eager handle."""
    with _handle_lock:
        entry = _handles.pop(handle, None)
    if entry is None:
        raise ValueError(f"unknown handle {handle}")
    eager_handle, output = entry
    result = _eager.synchronize(eager_handle)
    aux = getattr(eager_handle, "aux", {})
    if result is None:
        return output, aux
    out = _from_numpy(np.asarray(result))
    if output is not None:
        if output.shape != out.shape:
            output.resize_(out.shape)
        output.copy_(out.to(output.dtype))
        return output, aux
    return out, aux


# ---------------------------------------------------------------------------
# async API


def allreduce_async(tensor: torch.Tensor, average=None,
                    name: Optional[str] = None, op=None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0) -> int:
    h = _eager.allreduce_async(_to_numpy(tensor), average, name, op,
                               prescale_factor, postscale_factor)
    return _register(h, None)


def allreduce_async_(tensor: torch.Tensor, average=None,
                     name: Optional[str] = None, op=None,
                     prescale_factor: float = 1.0,
                     postscale_factor: float = 1.0) -> int:
    """In-place: the reduced result is written back into ``tensor`` at
    synchronize (reference: mpi_ops.py allreduce_async_)."""
    h = _eager.allreduce_async(_to_numpy(tensor), average, name, op,
                               prescale_factor, postscale_factor)
    return _register(h, tensor)


def allgather_async(tensor: torch.Tensor, name: Optional[str] = None) -> int:
    h = _eager.allgather_async(_to_numpy(tensor), name)
    return _register(h, None)


def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None) -> int:
    h = _eager.broadcast_async(_to_numpy(tensor), root_rank, name)
    return _register(h, None)


def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     name: Optional[str] = None) -> int:
    h = _eager.broadcast_async(_to_numpy(tensor), root_rank, name)
    return _register(h, tensor)


def alltoall_async(tensor: torch.Tensor, splits=None,
                   name: Optional[str] = None) -> int:
    if isinstance(splits, torch.Tensor):
        splits = splits.tolist()
    h = _eager.alltoall_async(_to_numpy(tensor), splits, name)
    return _register(h, None)


def grouped_allreduce_async(tensors, average=None, name: Optional[str] = None,
                            op=None, prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0) -> list:
    hs = _eager.grouped_allreduce_async([_to_numpy(t) for t in tensors],
                                        average, name, op,
                                        prescale_factor, postscale_factor)
    return [_register(h, None) for h in hs]


def grouped_allreduce_async_(tensors, average=None, name: Optional[str] = None,
                             op=None, prescale_factor: float = 1.0,
                             postscale_factor: float = 1.0) -> list:
    hs = _eager.grouped_allreduce_async([_to_numpy(t) for t in tensors],
                                        average, name, op,
                                        prescale_factor, postscale_factor)
    return [_register(h, t) for h, t in zip(hs, tensors)]


# ---------------------------------------------------------------------------
# autograd-aware sync API (reference: the torch.autograd.Function wrappers,
# torch/mpi_ops.py:163-181 allreduce grad = mirror allreduce; :538-558
# allgather grad = reduce + slice own rows; broadcast grad = reduce to root)


class _HorovodAllreduce(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, op, prescale_factor, postscale_factor, name):
        ctx.op = op
        ctx.prescale_factor = prescale_factor
        ctx.postscale_factor = postscale_factor
        return synchronize(allreduce_async(tensor, name=name, op=op,
                                           prescale_factor=prescale_factor,
                                           postscale_factor=postscale_factor))

    @staticmethod
    def backward(ctx, grad_output):
        g = synchronize(allreduce_async(grad_output, op=ctx.op,
                                        prescale_factor=ctx.prescale_factor,
                                        postscale_factor=ctx.postscale_factor))
        return g, None, None, None, None


class _HorovodAllgather(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, name):
        from horovod_tpu.common import basics
        ctx.dim0 = tensor.shape[0] if tensor.dim() > 0 else 1
        ctx.rank = basics._context().rank
        # Per-rank contributed row counts ride the handle's aux channel
        # (filled by the executor from the same allgatherv exchange), so the
        # backward slice offset needs no second collective.
        out, aux = _synchronize_with_aux(allgather_async(tensor, name=name))
        sizes = aux.get("rank_sizes")
        ctx.offset = (int(np.asarray(sizes)[:ctx.rank].sum())
                      if sizes is not None else 0)
        return out

    @staticmethod
    def backward(ctx, grad_output):
        g = synchronize(allreduce_async(grad_output, op=Sum))
        return g[ctx.offset:ctx.offset + ctx.dim0], None


class _HorovodBroadcast(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, root_rank, name):
        from horovod_tpu.common import basics
        ctx.root_rank = root_rank
        ctx.rank = basics._context().rank
        return synchronize(broadcast_async(tensor, root_rank, name=name))

    @staticmethod
    def backward(ctx, grad_output):
        g = synchronize(allreduce_async(grad_output, op=Sum))
        if ctx.rank != ctx.root_rank:
            g = torch.zeros_like(g)
        return g, None, None


class _HorovodAlltoall(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, splits, name):
        out, aux = _synchronize_with_aux(alltoall_async(tensor, splits, name))
        recv = aux.get("recv_splits")
        ctx.recv_splits = [int(x) for x in recv] if recv is not None else None
        return out

    @staticmethod
    def backward(ctx, grad_output):
        g = synchronize(alltoall_async(grad_output, ctx.recv_splits))
        return g, None, None


def allreduce(tensor: torch.Tensor, average=None, name: Optional[str] = None,
              compression=None, op=None, prescale_factor: float = 1.0,
              postscale_factor: float = 1.0) -> torch.Tensor:
    """Differentiable allreduce returning a new tensor (reference:
    mpi_ops.py allreduce — gradient is the mirror allreduce)."""
    from horovod_tpu.torch.compression import Compression
    compression = compression or Compression.none
    tensor_compressed, ctx = compression.compress(tensor)
    reduced = _HorovodAllreduce.apply(tensor_compressed, _eager.resolve_op(
        op, average), prescale_factor, postscale_factor, name)
    return compression.decompress(reduced, ctx)


def allreduce_(tensor: torch.Tensor, average=None,
               name: Optional[str] = None, op=None,
               prescale_factor: float = 1.0,
               postscale_factor: float = 1.0) -> torch.Tensor:
    return synchronize(allreduce_async_(tensor, average, name, op,
                                        prescale_factor, postscale_factor))


def allgather(tensor: torch.Tensor,
              name: Optional[str] = None) -> torch.Tensor:
    return _HorovodAllgather.apply(tensor, name)


def broadcast(tensor: torch.Tensor, root_rank: int,
              name: Optional[str] = None) -> torch.Tensor:
    return _HorovodBroadcast.apply(tensor, root_rank, name)


def broadcast_(tensor: torch.Tensor, root_rank: int,
               name: Optional[str] = None) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, name))


def alltoall(tensor: torch.Tensor, splits=None,
             name: Optional[str] = None) -> torch.Tensor:
    return _HorovodAlltoall.apply(tensor, splits, name)


def grouped_allreduce(tensors, average=None, name: Optional[str] = None,
                      op=None, prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0) -> list:
    handles = grouped_allreduce_async(tensors, average, name, op,
                                      prescale_factor, postscale_factor)
    return [synchronize(h) for h in handles]


def grouped_allreduce_(tensors, average=None, name: Optional[str] = None,
                       op=None, prescale_factor: float = 1.0,
                       postscale_factor: float = 1.0) -> list:
    handles = grouped_allreduce_async_(tensors, average, name, op,
                                       prescale_factor, postscale_factor)
    return [synchronize(h) for h in handles]


def join(device: int = -1) -> int:
    """Block until every rank joins; returns the last joined rank
    (reference: torch/mpi_ops.py:846+). ``device`` is accepted for API
    parity; the data plane is host-side so it is unused."""
    return _eager.join()


def barrier():
    _eager.barrier()
