"""Step-time attribution: decompose every training step into compute /
exposed-comm / negotiation-stall / host time, cross-rank.

The frontend step timer (``hvd_frontend_step_seconds`` wrapper,
``horovod_tpu.metrics.timed_step``) brackets every train-step invocation
with engine step marks (``hvdtpu_step_begin/end`` → STEP_BEGIN/STEP_END
flight events), and the flight recorder already black-boxes every
collective's lifecycle with per-response exec spans. This module turns
those two streams into the per-step answer the ROADMAP's perf items need
— total comm time is not the decisive metric, *exposed* (non-overlapped)
comm time on the critical path is (arXiv:1810.11112).

Decomposition model (documented in docs/DESIGN.md "Step attribution"):
within one step window ``[begin, end]`` on one rank,

- while the frontend is still **enqueueing** work it is also driving
  compute (dispatching the forward/backward that produces the next
  gradient), so everything up to the window's last ENQUEUE is
  ``compute``;
- after the last ENQUEUE the frontend only waits. Tail time covered by a
  collective's exec span is ``exposed_comm`` (comm the step actually
  waited on — the critical-path quantity); tail time spent between
  ENQUEUE and EXEC with no exec running is ``stall`` (negotiation /
  straggler wait); the remainder of the tail is ``host`` (result fetch,
  Python overhead);
- exec spans that overlap the enqueueing phase are ``overlapped_comm`` —
  comm the engine hid behind compute (free).

``compute + exposed_comm + stall + host == step`` exactly. A pure-jit
step (no engine-visible collectives — XLA's latency-hiding scheduler owns
the overlap) decomposes as 100% compute, which is honest: the engine can
only attribute the comm it routes.

Cross-rank, step windows pair by step id, clocks align via the flight
analyzer's shared CYCLE anchors, and the rank whose window ends last on
the aligned axis is the step's **critical-path rank** — its last-completing
collective is the gating tensor.

The ``step_attribution`` record this module emits (:func:`attribute`,
:func:`bench_block`) is the input contract for the ROADMAP autotuner PR:
stable keys, seconds, fractions of step time.
"""

from __future__ import annotations

import json
import threading
import time
from collections import Counter, deque
from typing import Dict, List, Optional, Tuple

from horovod_tpu.common.env_registry import (env_bool, env_float, env_int,
                                             env_str)
from horovod_tpu.profiler import flight as flight_mod

# Windows shorter than this many samples never fire the anomaly detector —
# mean/sigma over a handful of warmup steps is noise, not a baseline.
MIN_ANOMALY_SAMPLES = 8

# Sigma floor as a fraction of the rolling mean, mirroring the straggler
# detector: a perfectly uniform step trace (sigma -> 0) must not flag
# micro-jitter.
MIN_REL_SIGMA = 0.05


# ---------------------------------------------------------------------------
# interval arithmetic (all spans are (lo, hi) with lo <= hi, microseconds)


def _union(spans: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Merge overlapping/touching spans; drops empty ones."""
    out: List[Tuple[float, float]] = []
    for lo, hi in sorted(s for s in spans if s[1] > s[0]):
        if out and lo <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return out

def _span_len(spans: List[Tuple[float, float]]) -> float:
    return sum(hi - lo for lo, hi in spans)


def _clip(spans: List[Tuple[float, float]], lo: float,
          hi: float) -> List[Tuple[float, float]]:
    return [(max(s, lo), min(t, hi)) for s, t in spans
            if t > lo and s < hi]


def _subtract(spans: List[Tuple[float, float]],
              cut: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """``spans`` minus ``cut`` (both pre-unioned)."""
    out: List[Tuple[float, float]] = []
    for lo, hi in spans:
        cur = lo
        for clo, chi in cut:
            if chi <= cur or clo >= hi:
                continue
            if clo > cur:
                out.append((cur, clo))
            cur = max(cur, chi)
            if cur >= hi:
                break
        if cur < hi:
            out.append((cur, hi))
    return out


# ---------------------------------------------------------------------------
# flight-dump scanning


def step_windows(dump: dict) -> List[dict]:
    """Completed step windows of one rank's flight dump: STEP_BEGIN/END
    events paired by step id (aux). An unmatched BEGIN (step still running
    at dump time) or a BEGIN that fell off the ring is skipped."""
    begins: Dict[int, float] = {}
    out: List[dict] = []
    for e in dump.get("events", []):
        phase = e.get("phase")
        if phase == "STEP_BEGIN":
            begins[int(e.get("aux", -1))] = float(e.get("ts_us", 0))
        elif phase == "STEP_END":
            sid = int(e.get("aux", -1))
            begin = begins.pop(sid, None)
            if begin is not None:
                out.append({"step": sid, "begin_us": begin,
                            "end_us": float(e.get("ts_us", 0))})
    return sorted(out, key=lambda w: w["step"])


def _collective_spans(events: List[dict]):
    """One pass over a rank's event stream → the raw material of the
    decomposition: ENQUEUE timestamps, negotiation-wait spans
    (ENQUEUE/NEGOTIATE → EXEC) and exec spans (EXEC → DONE/DESYNC, with
    the DONE event's exec-span aux reconstructing a begin whose EXEC fell
    off the ring)."""
    enq: List[float] = []
    neg_open: Dict[str, float] = {}
    exec_open: Dict[str, float] = {}
    negs: List[Tuple[float, float]] = []
    execs: List[Tuple[float, float, str]] = []
    for ev in sorted(events, key=lambda x: x.get("i", 0)):
        phase = ev.get("phase", "")
        name = ev.get("name", "")
        if not name:
            continue  # CYCLE / STEP marks carry no collective lifecycle
        ts = float(ev.get("ts_us", 0))
        if phase == "ENQUEUE":
            enq.append(ts)
            neg_open[name] = ts
        elif phase == "NEGOTIATE":
            neg_open.setdefault(name, ts)
        elif phase == "EXEC":
            start = neg_open.pop(name, None)
            if start is not None:
                negs.append((start, ts))
            exec_open[name] = ts
        elif phase in ("DONE", "DESYNC"):
            start = exec_open.pop(name, None)
            if start is None:
                aux = float(ev.get("aux", 0))
                start = ts - aux if phase == "DONE" and aux > 0 else ts
            execs.append((start, ts, name))
            waited = neg_open.pop(name, None)
            if waited is not None:
                negs.append((waited, min(start, ts)))
    return enq, negs, execs


def _decompose_window(w: dict, enq: List[float],
                      negs: List[Tuple[float, float]],
                      execs: List[Tuple[float, float, str]]) -> dict:
    """One rank's decomposition of one step window (the model in the
    module docstring). All durations in seconds; the four buckets sum to
    ``step_s`` exactly."""
    b, e = w["begin_us"], w["end_us"]
    step_us = e - b
    comm = _union([(s, t) for s, t, _ in execs])
    comm = _clip(comm, b, e)
    comm_busy = _span_len(comm)
    in_enq = [t for t in enq if b <= t <= e]
    if in_enq:
        active_until = max(in_enq)
    elif comm or _clip(_union(list(negs)), b, e):
        # collectives from an earlier enqueue spill into this window: the
        # frontend was waiting on them from the start
        active_until = b
    else:
        # nothing engine-visible in the window — a pure-jit step (XLA owns
        # the overlap) is honest 100% compute, not host
        active_until = e
    tail_us = e - active_until
    exposed = _span_len(_clip(comm, active_until, e))
    neg_u = _clip(_union(list(negs)), active_until, e)
    stall = _span_len(_subtract(neg_u, comm))
    host = max(0.0, tail_us - exposed - stall)
    compute = step_us - tail_us
    gating = None
    gating_ts = None
    for s, t, name in execs:
        if b < t <= e and (gating_ts is None or t > gating_ts):
            gating_ts, gating = t, name
    sec = 1e-6
    return {
        "step": w["step"],
        "step_s": round(step_us * sec, 6),
        "compute_s": round(compute * sec, 6),
        "exposed_comm_s": round(exposed * sec, 6),
        "stall_s": round(stall * sec, 6),
        "host_s": round(host * sec, 6),
        "comm_busy_s": round(comm_busy * sec, 6),
        "overlapped_comm_s": round((comm_busy - exposed) * sec, 6),
        "collectives": sum(1 for s, t, _ in execs if t > b and s < e),
        "gating_tensor": gating,
    }


def decompose_rank(dump: dict) -> List[dict]:
    """Per-step decomposition of one rank's flight dump (rank-local
    clock)."""
    enq, negs, execs = _collective_spans(dump.get("events", []))
    return [_decompose_window(w, enq, negs, execs)
            for w in step_windows(dump)]


def attribute(dumps: Dict[int, dict]) -> dict:
    """Cross-rank step attribution over one job's per-rank flight dumps
    (the ``flight_rank<R>.json`` files, or in-memory ``flight_dump()``
    dicts keyed by rank).

    Reuses the flight analyzer's CYCLE-anchor clock alignment so per-rank
    step windows land on one axis; the rank whose window ends last is the
    step's critical-path rank. Returns the machine-readable
    ``step_attribution`` record::

        {"clock_offsets_us": {rank: off},
         "steps": [{"step", "critical_rank", "gating_tensor",
                    "step_skew_us", "ranks": {rank: decomposition}}],
         "summary": {"steps", "step_seconds_mean", "compute_frac",
                     "exposed_comm_frac", "stall_frac", "host_frac",
                     "overlapped_comm_frac", "critical_rank_counts",
                     "gating_tensor_counts"}}
    """
    offsets = flight_mod.align_clocks(dumps)
    by_step: Dict[int, Dict[int, dict]] = {}
    for r, d in sorted(dumps.items()):
        enq, negs, execs = _collective_spans(d.get("events", []))
        for w in step_windows(d):
            dec = _decompose_window(w, enq, negs, execs)
            dec["rank"] = r
            dec["end_aligned_us"] = round(
                w["end_us"] + offsets.get(r, 0.0), 1)
            by_step.setdefault(dec["step"], {})[r] = dec
    steps: List[dict] = []
    for sid, by_rank in sorted(by_step.items()):
        ends = {r: d["end_aligned_us"] for r, d in by_rank.items()}
        crit = max(ends, key=ends.get)
        steps.append({
            "step": sid,
            "critical_rank": crit,
            "gating_tensor": by_rank[crit]["gating_tensor"],
            "step_skew_us": round(max(ends.values()) - min(ends.values()),
                                  1),
            "ranks": by_rank,
        })
    return {
        "clock_offsets_us": {r: round(o, 1) for r, o in offsets.items()},
        "steps": steps,
        "summary": summarize(steps),
    }


def summarize(steps: List[dict]) -> dict:
    """Fleet-level rollup of per-step records (fractions of total step
    time, critical-path and gating-tensor counts)."""
    decs = [d for s in steps for d in s["ranks"].values()]
    total = sum(d["step_s"] for d in decs)
    if not decs or total <= 0:
        return {"steps": len(steps), "step_seconds_mean": None,
                "compute_frac": None, "exposed_comm_frac": None,
                "stall_frac": None, "host_frac": None,
                "overlapped_comm_frac": None,
                "critical_rank_counts": {}, "gating_tensor_counts": {}}

    def frac(key):
        return round(sum(d[key] for d in decs) / total, 4)

    return {
        "steps": len(steps),
        "step_seconds_mean": round(total / len(decs), 6),
        "compute_frac": frac("compute_s"),
        "exposed_comm_frac": frac("exposed_comm_s"),
        "stall_frac": frac("stall_s"),
        "host_frac": frac("host_s"),
        "overlapped_comm_frac": frac("overlapped_comm_s"),
        "critical_rank_counts": dict(Counter(
            s["critical_rank"] for s in steps)),
        "gating_tensor_counts": dict(Counter(
            s["gating_tensor"] for s in steps
            if s["gating_tensor"] is not None)),
    }


# ---------------------------------------------------------------------------
# live attribution + anomaly detection


class StepAttributor:
    """Process-local rolling step-time attribution, fed by the frontend
    step timer (one :meth:`step_begin`/:meth:`step_end` pair per train
    step, or plain :meth:`observe` for frontends that own their timing).

    Three jobs per step, all cheap enough for the hot path:

    - bracket the step with engine STEP marks (one lock-free flight
      Record each) so the flight ring carries the attribution windows;
    - rolling anomaly detection: a step exceeding
      ``mean + HOROVOD_ANOMALY_STDDEVS * sigma`` of the rolling window
      fires a structured log event, bumps ``hvd_step_anomaly_total`` and
      — when ``HOROVOD_FLIGHT_DIR`` is set — triggers an automatic flight
      dump, so the spike's post-mortem evidence is on disk before the
      ring wraps;
    - every ``HOROVOD_ATTRIBUTION_EVERY`` steps, decompose the latest
      completed window from the flight ring and export the result as
      ``hvd_step_*_seconds`` / ``hvd_step_exposed_comm_ratio`` gauges —
      what ``hvd-top`` and the elastic driver scrape. The refresh runs in
      a background thread (a full-ring dump costs tens of ms); the
      training thread only pays the thread kick.
    """

    def __init__(self, registry=None, engine=None, k: Optional[float] = None,
                 window: Optional[int] = None,
                 refresh_every: Optional[int] = None,
                 flight_dir: Optional[str] = None,
                 use_engine: bool = True):
        if registry is None:
            from horovod_tpu.metrics.registry import get_registry
            registry = get_registry()
        self._registry = registry
        self._engine = engine
        self._use_engine = use_engine
        self._k = k if k is not None else env_float("HOROVOD_ANOMALY_STDDEVS")
        self._window: deque = deque(
            maxlen=window if window is not None
            else max(MIN_ANOMALY_SAMPLES, env_int("HOROVOD_ANOMALY_WINDOW")))
        self._every = refresh_every if refresh_every is not None \
            else env_int("HOROVOD_ATTRIBUTION_EVERY")
        self._flight_dir = flight_dir if flight_dir is not None \
            else (env_str("HOROVOD_FLIGHT_DIR") or "")
        self._steps = 0
        # O(1) rolling mean/sigma over the window (statistics.pstdev's
        # exact-rational arithmetic costs ~300us per call — two orders of
        # magnitude over the whole per-step budget). Running float sums
        # drift as evicted values are subtracted back out; recomputed
        # exactly every window-length steps to bound the error.
        self._sum = 0.0
        self._sumsq = 0.0
        self._steps_observed = 0
        self._lock = threading.Lock()
        # periodic flight-ring decomposition runs OFF the training thread:
        # a full-ring dump + parse costs tens of ms, which would blow the
        # <1% step budget if paid inline even once every _every steps
        self._refresh_inflight = threading.Event()
        # instrument handles are resolved once — the per-step path must not
        # pay registry label-key lookups
        self._g_last = self._registry.gauge(
            "hvd_step_seconds_last",
            help="wall time of the most recent frontend step")
        self._c_anomalies = self._registry.counter(
            "hvd_step_anomaly_total",
            help="step-time spikes beyond HOROVOD_ANOMALY_STDDEVS "
                 "rolling sigmas")
        from horovod_tpu.common.hvd_logging import get_logger
        self._log = get_logger("obs.attribution")
        self.anomalies: List[dict] = []
        self.last_decomposition: Optional[dict] = None

    def _resolve_engine(self):
        if self._engine is not None:
            return self._engine
        if not self._use_engine:
            return None
        from horovod_tpu.common import basics
        return basics._context().engine

    def next_step(self) -> int:
        with self._lock:
            self._steps += 1
            return self._steps

    # -- the timed_step hook points -----------------------------------------

    def step_begin(self, step_id: int):
        engine = self._resolve_engine()
        if engine is not None:
            engine.step_begin(step_id)

    def step_end(self, step_id: int, seconds: float) -> Optional[dict]:
        """Close the step: engine STEP_END mark, anomaly check, periodic
        flight-ring decomposition refresh. Returns the anomaly event when
        one fired (also logged + appended to :attr:`anomalies`)."""
        engine = self._resolve_engine()
        if engine is not None:
            engine.step_end(step_id)
        event = self._observe(step_id, seconds, engine)
        if engine is not None and self._every > 0 and \
                step_id % self._every == 0:
            self._refresh_async(engine)
        return event

    def observe(self, seconds: float) -> Optional[dict]:
        """Frontend-only entry (no engine marks): frontends that own their
        timing — the torch optimizer, the keras callback — feed here."""
        return self._observe(self.next_step(), seconds,
                             self._resolve_engine())

    # -- internals ----------------------------------------------------------

    def _observe(self, step_id: int, seconds: float,
                 engine) -> Optional[dict]:
        event = None
        with self._lock:
            # the new sample is judged against the window that *precedes*
            # it — a spike must not dilute its own baseline
            n = len(self._window)
            mean = self._sum / n if n else 0.0
            var = max(0.0, self._sumsq / n - mean * mean) if n else 0.0
            if n == self._window.maxlen:
                old = self._window[0]
                self._sum -= old
                self._sumsq -= old * old
            self._window.append(seconds)
            self._sum += seconds
            self._sumsq += seconds * seconds
            self._steps_observed += 1
            if self._steps_observed % (self._window.maxlen or 64) == 0:
                self._sum = sum(self._window)
                self._sumsq = sum(x * x for x in self._window)
        if n >= MIN_ANOMALY_SAMPLES:
            sigma = max(var ** 0.5, MIN_REL_SIGMA * mean, 1e-9)
            threshold = mean + self._k * sigma
            if seconds > threshold:
                event = {
                    "event": "step_anomaly",
                    "step": step_id,
                    "step_time_sec": round(seconds, 6),
                    "rolling_mean_sec": round(mean, 6),
                    "rolling_sigma_sec": round(sigma, 6),
                    "threshold_sec": round(threshold, 6),
                    "stddevs": round((seconds - mean) / sigma, 2),
                }
        self._g_last.set(seconds)
        if event is not None:
            self._c_anomalies.inc()
            self.anomalies.append(event)
            self._log.warning("step anomaly: %s", json.dumps(event))
            if engine is not None and self._flight_dir:
                # the spike's evidence must hit disk before the ring wraps
                engine.flight_dump(self._flight_dir)
        return event

    def _refresh_async(self, engine):
        """Kick one background decomposition refresh; a refresh still in
        flight is simply skipped (the gauges are a sampled view, not a
        ledger — fresher data is already on its way)."""
        if self._refresh_inflight.is_set():
            return
        self._refresh_inflight.set()

        def run():
            try:
                self.refresh_decomposition(engine)
            except Exception:  # noqa: BLE001 — telemetry thread; a
                pass  # malformed dump must not leak a traceback mid-train
            finally:
                self._refresh_inflight.clear()

        threading.Thread(target=run, daemon=True,
                         name="hvd-attribution-refresh").start()

    def refresh_decomposition(self, engine=None) -> Optional[dict]:
        """Decompose the latest completed step window from the engine's
        flight ring and export it as gauges. Returns the decomposition (or
        None without an engine / completed window)."""
        engine = engine if engine is not None else self._resolve_engine()
        if engine is None:
            return None
        dump = engine.flight_dump()
        if not dump:
            return None
        windows = step_windows(dump)
        if not windows:
            return None
        enq, negs, execs = _collective_spans(dump.get("events", []))
        dec = _decompose_window(windows[-1], enq, negs, execs)
        self.last_decomposition = dec
        g = self._registry.gauge
        g("hvd_step_compute_seconds",
          help="per-step compute time (frontend still enqueueing)").set(
              dec["compute_s"])
        g("hvd_step_exposed_comm_seconds",
          help="per-step exposed (non-overlapped) collective time").set(
              dec["exposed_comm_s"])
        g("hvd_step_stall_seconds",
          help="per-step negotiation/straggler wait").set(dec["stall_s"])
        g("hvd_step_host_seconds",
          help="per-step host-side remainder").set(dec["host_s"])
        g("hvd_step_exposed_comm_ratio",
          help="exposed comm as a fraction of step time").set(
              dec["exposed_comm_s"] / dec["step_s"]
              if dec["step_s"] > 0 else 0.0)
        return dec


_attributor: Optional[StepAttributor] = None
_attr_lock = threading.Lock()


def get_attributor() -> Optional[StepAttributor]:
    """The process-global attributor, or None when
    ``HOROVOD_STEP_ATTRIBUTION=0``. Lazily created on first use (after
    init, so the engine session resolves)."""
    if not env_bool("HOROVOD_STEP_ATTRIBUTION"):
        return None
    global _attributor
    with _attr_lock:
        if _attributor is None:
            _attributor = StepAttributor()
        return _attributor


# ---------------------------------------------------------------------------
# BENCH json block


def bench_block(step_seconds_by_model: Dict[str, float]) -> dict:
    """The BENCH json ``step_attribution`` block: per-model decomposition
    plus a measured attribution-overhead figure.

    ``step_seconds_by_model`` maps model name → measured per-step wall
    seconds. With a live engine session the per-model buckets come from
    the flight ring's summary fractions; a single-process bench (no
    engine — XLA owns the overlap inside the jitted step) decomposes as
    100% compute with the source field saying so. Overhead: the
    attributor's per-step observe cost (anomaly window + gauge update),
    measured directly, as a percentage of each model's step — the <1%
    acceptance budget."""
    from horovod_tpu.metrics.registry import MetricsRegistry
    probe = StepAttributor(registry=MetricsRegistry(), use_engine=False,
                           flight_dir="")
    iters = 5000
    t0 = time.perf_counter()
    for _ in range(iters):
        probe.observe(0.1)
    per_observe_s = (time.perf_counter() - t0) / iters

    from horovod_tpu.common import basics
    engine = basics._context().engine
    record = None
    refresh_s = None
    if engine is not None:
        t0 = time.perf_counter()
        dump = engine.flight_dump()
        if dump:
            record = attribute({int(dump.get("rank", 0)): dump})
        # one full dump + decomposition — the background refresh's cost
        # (paid off the training thread, HOROVOD_ATTRIBUTION_EVERY apart)
        refresh_s = time.perf_counter() - t0
    summary = record["summary"] if record else None
    live = bool(summary and summary["steps"])
    source = ("flight-ring decomposition (this rank's engine; cross-rank "
              "critical path needs every rank's dump — see "
              "horovod_tpu.obs.attribute)" if live else
              "frontend-only: no engine session in this process, in-jit "
              "collectives are overlapped by XLA and invisible to the "
              "engine, so the step decomposes as compute")

    per_model = {}
    for model, step_s in step_seconds_by_model.items():
        if not step_s or step_s <= 0:
            continue
        if live:
            entry = {
                "step_seconds": round(step_s, 6),
                "compute_s": round(step_s * summary["compute_frac"], 6),
                "exposed_comm_s": round(
                    step_s * summary["exposed_comm_frac"], 6),
                "stall_s": round(step_s * summary["stall_frac"], 6),
                "host_s": round(step_s * summary["host_frac"], 6),
                "critical_rank": max(
                    summary["critical_rank_counts"],
                    key=summary["critical_rank_counts"].get),
            }
        else:
            entry = {"step_seconds": round(step_s, 6),
                     "compute_s": round(step_s, 6),
                     "exposed_comm_s": 0.0, "stall_s": 0.0, "host_s": 0.0,
                     "critical_rank": 0}
        entry["attribution_overhead_pct_of_step"] = round(
            100.0 * per_observe_s / step_s, 5)
        per_model[model] = entry

    return {
        "source": source,
        "per_model": per_model,
        "summary": summary,
        "attribution_overhead": {
            "seconds_per_step_observe": round(per_observe_s, 9),
            "seconds_per_ring_refresh": (round(refresh_s, 6)
                                         if refresh_s is not None else None),
            "refresh_note": "ring refresh runs on a background thread "
                            "every HOROVOD_ATTRIBUTION_EVERY steps; the "
                            "training thread pays only the per-step "
                            "observe cost",
            "budget_pct": 1.0,
        },
    }
