"""Distributed request tracing for the serving plane (ISSUE 18).

The serving analog of the per-collective flight recorder and the engine
timeline: a *sampled* trace id is minted at frontend ingress
(``HOROVOD_TRACE_SAMPLE``, default 0.0 — off), flows through router →
worker → batcher → kv_cache → executor in the request payload's
``"trace"`` field, and every stage emits a Chrome-trace complete ("X")
span into a bounded in-process ring buffer. Span export rides the PR-5
``trace_merge`` path, so one Perfetto-loadable file shows a request's
admission, queue wait, cache lookup, prefill, draft/verify and decode
steps beside engine/device activity.

Span inventory (``tid`` is the component lane)::

    admission     frontend   quota/class shedding + batcher submit
    queue_wait    batcher    arrival -> first scheduling into a batch
    cache_lookup  kv_cache   prefix-hash lookup + pool charge at admit
    prefill       executor   prompt consumption (first cached advance)
    draft         executor   draft-model proposal micro-steps
    verify        executor   target verification of drafted tokens
    decode_step   executor   one steady-state decode step
    re_route      router     dispatch retry after a worker death

Sampling rules: the decision is made ONCE, at ingress — downstream
stages *adopt* an inbound trace id and never re-sample (a request is
either fully traced or not at all). Unsampled requests take a
single-pointer fast path (``req.trace is None``) so tracing at 0% is
free and at 1% costs <1% p50 (BENCH ``telemetry`` block). The trace id
is echoed as ``trace_id`` in every HTTP response — including 429
rejections — for client-side correlation.

The buffer is a bounded deque (``HOROVOD_TRACE_BUFFER_SPANS``): tracing
is diagnostic, never a memory leak; old spans fall off the back.
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from collections import deque
from typing import List, Optional

from horovod_tpu.common.env_registry import env_float, env_int, env_str

# Span kinds (Chrome-trace event names).
ADMISSION = "admission"
QUEUE_WAIT = "queue_wait"
CACHE_LOOKUP = "cache_lookup"
PREFILL = "prefill"
DRAFT = "draft"
VERIFY = "verify"
DECODE_STEP = "decode_step"
RE_ROUTE = "re_route"

SPAN_KINDS = (ADMISSION, QUEUE_WAIT, CACHE_LOOKUP, PREFILL, DRAFT,
              VERIFY, DECODE_STEP, RE_ROUTE)


def now_us() -> float:
    """Wall-clock microseconds. Spans from different processes share the
    epoch timebase, so a merged cross-process timeline is aligned to NTP
    accuracy (same caveat as trace_merge's engine/JAX clock note)."""
    return time.time() * 1e6


class _Span:
    """Context manager that records one complete ("X") event on exit."""

    __slots__ = ("_tracer", "_trace_id", "kind", "lane", "args", "_t0",
                 "_w0")

    def __init__(self, tracer: "Tracer", trace_id: str, kind: str,
                 lane: str, args: dict):
        self._tracer = tracer
        self._trace_id = trace_id
        self.kind = kind
        self.lane = lane
        self.args = args

    def __enter__(self) -> "_Span":
        self._w0 = now_us()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = (time.perf_counter() - self._t0) * 1e6
        if exc is not None:
            self.args = dict(self.args, error=repr(exc))
        self._tracer.record(self._trace_id, self.kind, self.lane,
                            self._w0, dur, **self.args)
        return False


class _NullSpan:
    """The unsampled fast path: enter/exit are attribute loads only."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL_SPAN = _NullSpan()


class Tracer:
    """Bounded span buffer + sampling decision for one process."""

    def __init__(self, sample: Optional[float] = None,
                 buffer_spans: Optional[int] = None):
        self.sample = sample if sample is not None \
            else env_float("HOROVOD_TRACE_SAMPLE")
        cap = buffer_spans if buffer_spans is not None \
            else env_int("HOROVOD_TRACE_BUFFER_SPANS")
        self._spans: deque = deque(maxlen=max(1, int(cap)))
        self._lock = threading.Lock()
        self._rng = random.Random()

    # -- sampling / propagation ---------------------------------------------

    def maybe_trace(self) -> Optional[str]:
        """The ingress sampling decision: a fresh trace id with
        probability ``sample``, else None (request untraced)."""
        if self.sample <= 0.0 or self._rng.random() >= self.sample:
            return None
        return uuid.uuid4().hex[:16]

    def adopt_or_start(self, body: dict) -> Optional[str]:
        """Trace id for one inbound request body: adopt the upstream
        decision when the payload carries one (worker behind an ingress
        router — never re-sample), else make the ingress decision."""
        trace = body.get("trace")
        if isinstance(trace, dict) and trace.get("id"):
            return str(trace["id"])
        if isinstance(trace, str) and trace:
            return trace
        return self.maybe_trace()

    @staticmethod
    def inject(body: dict, trace_id: Optional[str]) -> dict:
        """Propagate a trace id into an outbound request payload."""
        if trace_id is None:
            return body
        return dict(body, trace={"id": trace_id})

    # -- span emission -------------------------------------------------------

    def span(self, trace_id: Optional[str], kind: str, lane: str, **args):
        """Context manager emitting one span; free no-op when untraced."""
        if trace_id is None:
            return _NULL_SPAN
        return _Span(self, trace_id, kind, lane, args)

    def record(self, trace_id: Optional[str], kind: str, lane: str,
               ts_us: float, dur_us: float, **args):
        """Append one complete span (explicit timestamps — for spans
        whose start predates the call site, e.g. queue_wait)."""
        if trace_id is None:
            return
        event = {"name": kind, "ph": "X", "ts": float(ts_us),
                 "dur": max(0.0, float(dur_us)), "tid": lane,
                 "args": dict(args, trace=trace_id)}
        with self._lock:
            self._spans.append(event)

    # -- collection / export -------------------------------------------------

    def spans(self, trace_id: Optional[str] = None) -> List[dict]:
        with self._lock:
            out = list(self._spans)
        if trace_id is not None:
            out = [e for e in out
                   if e.get("args", {}).get("trace") == trace_id]
        return out

    def clear(self):
        with self._lock:
            self._spans.clear()

    def export(self, out_path=None, trace_id: Optional[str] = None,
               extra_spans: Optional[List[dict]] = None,
               label: str = "horovod serving") -> dict:
        """One Perfetto-loadable trace via the PR-5 merge path.

        ``extra_spans`` lets a collector fold in spans fetched from OTHER
        processes (e.g. a worker's ``GET /trace.json``) so the frontend
        and executor halves of a routed request land in one timeline.
        Default ``out_path`` lands under ``HOROVOD_TRACE_DIR`` when set.
        """
        from horovod_tpu.profiler.trace_merge import merge_traces
        events = self.spans(trace_id) + [
            e for e in (extra_spans or [])
            if trace_id is None or e.get("args", {}).get("trace") == trace_id]
        if out_path is None:
            trace_dir = env_str("HOROVOD_TRACE_DIR")
            if trace_dir:
                import os
                os.makedirs(trace_dir, exist_ok=True)
                out_path = os.path.join(
                    trace_dir, f"trace_{trace_id or 'all'}.json")
        return merge_traces(events, out_path=out_path, engine_label=label)


_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    """The process-global tracer (lazy, env-configured — the
    ``get_registry`` pattern)."""
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                _tracer = Tracer()
    return _tracer


def configure(sample: Optional[float] = None,
              buffer_spans: Optional[int] = None) -> Tracer:
    """Replace the global tracer (tests; runtime re-configuration)."""
    global _tracer
    with _tracer_lock:
        _tracer = Tracer(sample=sample, buffer_spans=buffer_spans)
    return _tracer
