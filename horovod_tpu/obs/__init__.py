"""Live observability: step-time attribution and the ``hvd-top`` view.

The third layer of the observability stack, and the one that finally
*consumes* the signals the other two produce:

- the PR-3 **monitoring** layer (``horovod_tpu/metrics``) counts and
  exports — counters, gauges, histograms, the per-worker ``/metrics``
  endpoint;
- the PR-5 **post-mortem** layer (flight recorder + analyzer) explains
  failures after the fact;
- this **attribution** layer answers "where did my step go" while the job
  is alive: per-step compute / exposed-comm / negotiation-stall / host
  decomposition (:mod:`horovod_tpu.obs.attribution`), rolling step-time
  anomaly detection with automatic flight dumps, and the ``hvd-top``
  cluster view (:mod:`horovod_tpu.obs.top`).
"""

from __future__ import annotations

from horovod_tpu.obs.attribution import (  # noqa: F401
    StepAttributor,
    attribute,
    bench_block,
    decompose_rank,
    get_attributor,
    step_windows,
)
