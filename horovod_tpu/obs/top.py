"""``hvd-top``: live per-rank cluster view over worker metrics endpoints.

Scrapes every rank's ``/metrics.json`` (the endpoint
``HOROVOD_METRICS_PORT`` turns on) and renders one row per rank:

- **STEP ms** — mean frontend step time over the refresh window (the
  shared ``hvd_frontend_step_seconds`` histogram, diffed between
  scrapes; ``--once`` shows the lifetime mean);
- **EXP%** / **STALL%** — exposed-comm and negotiation-stall fractions of
  the step (the attribution gauges, :mod:`horovod_tpu.obs.attribution`);
- **CACHE%** — engine response-cache hit rate;
- **FUSE** — mean tensors per fused response;
- **QD** — engine tensor-queue depth;
- **STRAG** — peer-relative step-time skew in sigmas (the same
  leave-one-out math the elastic driver uses, computed from the scraped
  window means);
- **ANOM** — step-anomaly count (``hvd_step_anomaly_total``).

Targets, in priority order: ``--targets host:port[,host:port...]``; the
rendezvous KV's ``metrics_targets`` key (published by the elastic driver
every heartbeat) via ``--kv host:port`` or
``HOROVOD_RENDEZVOUS_ADDR``/``PORT``; failing both, ``localhost`` with
``HOROVOD_METRICS_PORT`` + local rank offsets.

``--once`` prints a single snapshot and exits (CI/tests; exit 1 when no
target answered). The live view refreshes every ``HOROVOD_TOP_INTERVAL``
seconds, through curses when stdout is a TTY (``--plain`` forces the
dumb redraw loop; no curses dependency is required anywhere).

``--serving`` switches to the request-plane view (per-rank QPS over the
refresh window, queue depth, in-flight count, mean batch occupancy,
p50/p99 request latency, ok/reject/expired totals — the ``hvd_serve_*``
families the serving plane exports on the same endpoints).

``--tune`` switches to the autotuner view (current bucket bytes / fusion
threshold / cycle time / express-lane class / compression, search phase,
last and best exposed-comm objective, samples spent — the ``hvd_tune_*``
gauges the frontend tuner exports, :mod:`horovod_tpu.tune`).

``--autoscale`` switches to the autoscaler view: a banner with the fleet
size and the last scaling decision (action, state, reason, age — the
epoch-claimed ``autoscale/decision`` KV record, when ``--kv`` or the
rendezvous env points at the KV), then per-rank queue depth, in-flight,
p99, SLO headroom (the policy's own :func:`slo_headroom` formula) and
the admission plane's per-class admit/shed counters.

**Host rollup (the 1024-rank view, ISSUE 18):** when the fleet exceeds
``HOROVOD_TOP_ROLLUP_RANKS`` ranks and the KV publishes ``agg_targets``
(the per-host aggregator endpoints of the tiered telemetry plane), the
default view scrapes H ``/agg.json`` endpoints instead of N
``/metrics.json`` ones and renders one row per host: rank count, window
step mean AND p99 (from the host-merged step histogram), mean EXP% /
STALL% over the per-rank gauge vectors, summed queue depth and anomaly
total, the aggregator's own scrape-error count, and the payload age —
age-marked ``!`` plus a ``STALE DATA`` banner past
``HOROVOD_AGG_STALE_SECONDS`` (the same bound the driver's fallback
uses). ``--rollup`` forces the host view below the threshold,
``--no-rollup`` forces per-rank rows above it, and ``--rank <r>``
drills down to the per-rank view of one rank, resolved through the
aggregator tier's per-rank vectors (no O(N) scrape).

CLI::

    hvd-top --targets 127.0.0.1:9090,127.0.0.1:9091
    hvd-top --serving --kv 127.0.0.1:8888
    hvd-top --kv 127.0.0.1:8888 --rank 371
    python -m horovod_tpu.obs.top --once --targets 127.0.0.1:9090
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List, Optional, Tuple

from horovod_tpu.common.env_registry import (env_float, env_int, env_is_set,
                                             env_str)
from horovod_tpu.metrics import STEP_SECONDS, snapshot_value, step_stats
from horovod_tpu.metrics.straggler import StragglerDetector

COLUMNS = ("RANK", "STEP ms", "EXP%", "STALL%", "CACHE%", "FUSE", "QD",
           "STRAG", "ANOM")
_FMT = "{:>5} {:>9} {:>6} {:>7} {:>7} {:>6} {:>5} {:>7} {:>5}"

# Serving view (--serving): the request-plane health of each rank, scraped
# from the same /metrics.json endpoints — QPS is the ok-request rate over
# the refresh window (lifetime totals on --once show as OK), OCC the mean
# batch occupancy, p50/p99 from the request-latency histogram, REJ/EXP the
# backpressure and deadline counters. The fast-path trio reads the
# hvd_serve_cache_* families: HIT% the shared-prefix lookup hit rate,
# BLOCKS the used/pool block ratio of the paged KV cache, REUSE the
# shared-block incref count (requests that skipped prefill compute).
SERVING_COLUMNS = ("RANK", "QPS", "QD", "INFL", "OCC", "p50ms", "p99ms",
                   "OK", "REJ", "EXP", "HIT%", "BLOCKS", "REUSE")
_SERVING_FMT = ("{:>5} {:>7} {:>4} {:>5} {:>5} {:>8} {:>8} {:>7} {:>6} "
                "{:>6} {:>6} {:>9} {:>7}")

# Tune view (--tune): the frontend autotuner's live state per rank, from
# the hvd_tune_* gauges (horovod_tpu/tune). BUCKET/FUSE/CYC/LANE are the
# currently applied knobs, ALGO the data-plane routing decision
# ("hier+rd@1M" = hierarchical on, recursive-doubling small route, ring
# threshold 1M — the cycle-fenced TunedParams routing trio), PHASE the
# search stage, OBJ/BEST the last and best measured exposed-comm
# objective, N the samples spent.
TUNE_COLUMNS = ("RANK", "BUCKET", "FUSE MB", "CYC ms", "LANE", "ALGO",
                "COMP", "PHASE", "OBJ ms", "BEST ms", "N")
_TUNE_FMT = ("{:>5} {:>9} {:>8} {:>7} {:>6} {:>12} {:>5} {:>9} {:>8} "
             "{:>8} {:>4}")
_TUNE_PHASES = {0: "warmup", 1: "sweep", 2: "refine", 3: "converged"}
_TUNE_COMP = {0: "none", 1: "bf16", 2: "int8"}
_TUNE_SMALL_ALGO = {0: "star", 1: "rd"}

# Autoscale view (--autoscale): per-rank serving SLO headroom + the
# admission plane's per-class counters, plus a banner line carrying the
# fleet size and the autoscaler's last decision (reason + age) when a
# rendezvous KV is reachable (the epoch-claimed autoscale/decision
# record). HEADRM is the shared slo_headroom() formula the policy's
# breach test uses: 1.0 idle, 0.0 at the bound, negative = breached.
AUTOSCALE_COLUMNS = ("RANK", "QD", "INFL", "p99ms", "HEADRM", "ADM",
                     "SHED", "QUOTA")
_AUTOSCALE_FMT = "{:>5} {:>5} {:>5} {:>8} {:>7} {:>8} {:>7} {:>6}"

# Host-rollup view: one row per host from its aggregator's /agg.json —
# the O(hosts) rendering the tiered telemetry plane exists for. STEP ms
# is the window mean of the host-merged step histogram, p99 its
# interpolated quantile (the merge is bucket-wise, so the host p99 is a
# real cross-rank quantile, not a mean of means); EXP%/STALL% average
# the per-rank gauge vectors; QD/ANOM sum; ERR is the aggregator's own
# scrape-error count for the window; AGE the payload age, "!"-marked
# past the staleness bound.
ROLLUP_COLUMNS = ("HOST", "RANKS", "STEP ms", "p99 ms", "EXP%", "STALL%",
                  "QD", "ANOM", "ERR", "AGE s")
_ROLLUP_FMT = ("{:>12} {:>5} {:>9} {:>9} {:>6} {:>7} {:>5} {:>5} {:>4} "
               "{:>7}")


def _parse_hostports(arg: str) -> List[dict]:
    out = []
    for item in arg.split(","):
        item = item.strip()
        if not item:
            continue
        host, _, port = item.rpartition(":")
        try:
            out.append({"addr": host or "127.0.0.1", "port": int(port)})
        except ValueError:
            raise ValueError(
                f"invalid metrics target {item!r} (want host:port or "
                f"a bare port)") from None
    return out


def _kv_coords(args) -> Optional[Tuple[str, int]]:
    """(host, port) of the rendezvous KV per --kv / the env, or None.
    With a replicated ``--kv a:1,b:2,c:3`` list the first endpoint is
    the coordinate (reads fail over via the endpoint list anyway)."""
    if args.kv:
        first = args.kv.split(",")[0].strip()
        host, _, port = first.rpartition(":")
        try:
            return (host or "127.0.0.1", int(port))
        except ValueError:
            raise ValueError(
                f"invalid --kv address {args.kv!r} (want host:port)") \
                from None
    if env_str("HOROVOD_RENDEZVOUS_ADDR") and \
            env_int("HOROVOD_RENDEZVOUS_PORT"):
        return (env_str("HOROVOD_RENDEZVOUS_ADDR"),
                env_int("HOROVOD_RENDEZVOUS_PORT"))
    return None


def _kv_endpoints(args) -> Optional[List[str]]:
    """The replica endpoint list (for the KV health banner): a
    comma-separated ``--kv``, else ``HOROVOD_KV_REPLICA_ENDPOINTS``."""
    if args.kv and "," in args.kv:
        return [e.strip() for e in args.kv.split(",") if e.strip()]
    eps = env_str("HOROVOD_KV_REPLICA_ENDPOINTS")
    if eps:
        return [e.strip() for e in eps.split(",") if e.strip()]
    return None


def kv_health(endpoints: List[str]) -> dict:
    """One ``/replica_status`` probe per replica, folded into the
    banner doc: ``leader`` (replica id, None when no leaseholder
    answered), its endpoint/epoch/lease age, per-shard WAL bytes, and
    replica liveness (``up``/``total``)."""
    from horovod_tpu.runner.replica_kv import replica_statuses
    sts = replica_statuses(endpoints, timeout=1.0)
    doc = {"up": sum(1 for st in sts.values() if st),
           "total": len(endpoints), "leader": None}
    for ep, st in sts.items():
        if st and st.get("role") == "leader":
            doc.update(leader=st.get("id"), endpoint=ep,
                       epoch=st.get("epoch"),
                       lease_age=st.get("lease_age", 0.0),
                       lease_seconds=st.get("lease_seconds", 0.0),
                       shards=st.get("shards", {}))
            break
    return doc


def _fmt_bytes(n) -> str:
    n = float(n)
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f}MB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f}KB"
    return f"{int(n)}B"


def render_doctor_banner(journal_dir) -> Optional[str]:
    """The newest ``hvd-doctor`` verdict's age + incident count, when a
    journal directory carries one (``doctor_verdict.json``). None = no
    verdict yet — no banner line."""
    from horovod_tpu.obs import doctor
    verdict = doctor.read_verdict_file(journal_dir)
    if not verdict:
        return None
    age = max(0.0, time.time() - float(verdict.get("generated_at", 0.0)))
    age_s = f"{age / 3600:.1f}h" if age >= 3600 else f"{age:.0f}s"
    n = int(verdict.get("incident_count", 0))
    if not n:
        return f"doctor: healthy (verdict {age_s} old)"
    return (f"*** doctor: {n} incident(s), top cause "
            f"{verdict.get('top_cause')} (verdict {age_s} old — rerun "
            f"hvd-doctor for a fresh one) ***")


def render_kv_banner(h: dict) -> str:
    if h["leader"] is None:
        return (f"*** KV: NO LEADER reachable ({h['up']}/{h['total']} "
                f"replicas up) — control plane suspect ***")
    shards = " ".join(f"{s}:{_fmt_bytes(b)}"
                      for s, b in sorted(h.get("shards", {}).items()))
    return (f"KV: leader r{h['leader']}@{h['endpoint']} "
            f"epoch {h['epoch']} "
            f"lease {h['lease_age']:.1f}/{h['lease_seconds']:.1f}s "
            f"replicas {h['up']}/{h['total']} up  WAL {shards}")


def discover_targets(args) -> List[dict]:
    """[{addr, port, rank?}] per the priority order in the module doc."""
    if args.targets:
        return _parse_hostports(args.targets)
    kv = _kv_coords(args)
    if kv is not None:
        from horovod_tpu.runner.http_kv import KVClient
        from horovod_tpu.common import kv_keys
        targets = KVClient(*kv).get_json(kv_keys.metrics_targets(),
                                         timeout=3.0)
        if targets:
            return list(targets)
    if env_is_set("HOROVOD_METRICS_PORT"):
        base = env_int("HOROVOD_METRICS_PORT")
        if base > 0:
            return [{"addr": "127.0.0.1", "port": base + lr}
                    for lr in range(max(1, env_int("HOROVOD_LOCAL_SIZE")))]
    return []


def discover_agg_targets(args) -> List[dict]:
    """Per-host aggregator endpoints ``[{host, addr, port, ...}]`` from
    the KV's ``agg_targets`` record (published by the elastic driver
    every heartbeat for hosts consumed via the tier). Empty when no KV
    is reachable or the tier is off — callers fall back to per-rank
    targets."""
    kv = _kv_coords(args)
    if kv is None:
        return []
    from horovod_tpu.common import kv_keys
    from horovod_tpu.runner.http_kv import KVClient
    record = KVClient(*kv).get_json(kv_keys.agg_targets(), timeout=3.0)
    if not isinstance(record, dict):
        return []
    return [h for h in record.get("hosts", []) if isinstance(h, dict)
            and h.get("addr") and h.get("port")]


def scrape_agg(target: dict, timeout: float = 2.0) -> Optional[dict]:
    """One host aggregator's /agg.json payload, or None (a dead
    aggregator must not take down the rollup — its host just shows as
    unreachable while the driver's fallback covers its ranks)."""
    from urllib import error as urlerror
    from urllib import request as urlrequest
    url = f"http://{target['addr']}:{target['port']}/agg.json"
    try:
        with urlrequest.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read())
    except (urlerror.URLError, ConnectionError, OSError, ValueError):
        return None


def resolve_rank_target(agg_targets: List[dict], targets: List[dict],
                        rank: int) -> Optional[dict]:
    """--rank drill-down: one rank's direct /metrics.json endpoint,
    resolved from the aggregator tier's per-rank vectors (each carries
    the rank's addr/port) — O(hosts), not O(ranks) — falling back to a
    rank-labelled entry in the per-rank target list."""
    for agg in agg_targets:
        payload = scrape_agg(agg)
        if payload is None:
            continue
        for vec in payload.get("ranks", {}).values():
            if not isinstance(vec, dict) or vec.get("rank") != rank:
                continue
            addr = vec.get("addr")
            if addr in (None, "", "127.0.0.1", "localhost"):
                # the aggregator scraped loopback; reach the rank
                # through its host's externally visible address
                addr = agg["addr"]
            if vec.get("port"):
                return {"addr": addr, "port": vec["port"], "rank": rank}
    for t in targets:
        if t.get("rank") == rank:
            return t
    return None


def scrape_target(target: dict, timeout: float = 1.0) -> Optional[dict]:
    """One rank's /metrics.json snapshot, or None when unreachable (a
    worker mid-restart must not take down the view)."""
    from urllib import error as urlerror
    from urllib import request as urlrequest
    url = f"http://{target['addr']}:{target['port']}/metrics.json"
    try:
        with urlrequest.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read())
    except (urlerror.URLError, ConnectionError, OSError, ValueError):
        return None


def _rank_of(target: dict, snap: dict) -> str:
    rank = snap.get("labels", {}).get("rank")
    if rank in (None, ""):
        rank = target.get("rank")
    return str(rank) if rank is not None else f"?{target['port']}"


def row_from_snapshot(target: dict, snap: dict,
                      prev_steps: Optional[Tuple[int, float]]) -> dict:
    """Extract one display row. ``prev_steps`` is the (count, sum) of the
    step histogram at the previous refresh — None means lifetime mean."""
    stats = step_stats(snap)
    step_ms = None
    if stats is not None:
        count, total = stats
        if prev_steps is not None and count > prev_steps[0]:
            step_ms = 1e3 * (total - prev_steps[1]) / (count - prev_steps[0])
        elif prev_steps is None and count:
            step_ms = 1e3 * total / count
    step_last = snapshot_value(snap, "hvd_step_seconds_last")
    exp_ratio = snapshot_value(snap, "hvd_step_exposed_comm_ratio")
    stall_s = snapshot_value(snap, "hvd_step_stall_seconds")
    hits = snapshot_value(snap, "hvd_engine_cache_hits_total") or 0.0
    misses = snapshot_value(snap, "hvd_engine_cache_misses_total") or 0.0
    fused = snapshot_value(snap, "hvd_engine_fused_tensors_total")
    responses = snapshot_value(snap, "hvd_engine_responses_total")
    return {
        "rank": _rank_of(target, snap),
        "step_ms": step_ms,
        "step_seconds": step_ms / 1e3 if step_ms is not None else None,
        "exposed_pct": 100.0 * exp_ratio if exp_ratio is not None else None,
        "stall_pct": (100.0 * stall_s / step_last
                      if stall_s is not None and step_last else None),
        "cache_pct": (100.0 * hits / (hits + misses)
                      if hits + misses else None),
        "fuse": (fused / responses if fused is not None and responses
                 else None),
        "queue_depth": snapshot_value(snap, "hvd_engine_queue_depth"),
        "anomalies": snapshot_value(snap, "hvd_step_anomaly_total") or 0.0,
        "steps_raw": stats,
    }


def serving_row_from_snapshot(target: dict, snap: dict,
                              prev: Optional[Tuple[float, float]]) -> dict:
    """One serving-view row. ``prev`` is (monotonic_ts, ok_count) at the
    previous refresh; None (--once) leaves QPS blank and shows lifetime
    totals instead."""
    from horovod_tpu.metrics import histogram_quantile, snapshot_histogram
    now = time.monotonic()
    ok = snapshot_value(snap, "hvd_serve_requests_total", status="ok") or 0.0
    qps = None
    if prev is not None and now > prev[0]:
        qps = max(0.0, ok - prev[1]) / (now - prev[0])
    lat = snapshot_histogram(snap, "hvd_serve_request_latency_seconds")
    occ = snapshot_histogram(snap, "hvd_serve_batch_occupancy")
    p50 = histogram_quantile(lat, 0.5) if lat else None
    p99 = histogram_quantile(lat, 0.99) if lat else None
    lookups = snapshot_value(snap, "hvd_serve_cache_lookups_total")
    hits = snapshot_value(snap, "hvd_serve_cache_hits_total")
    used = snapshot_value(snap, "hvd_serve_cache_blocks_used")
    pool = snapshot_value(snap, "hvd_serve_cache_pool_blocks")
    return {
        "rank": _rank_of(target, snap),
        "qps": qps,
        "queue_depth": snapshot_value(snap, "hvd_serve_queue_depth"),
        "inflight": snapshot_value(snap, "hvd_serve_inflight"),
        "occupancy": occ["sum"] / occ["count"] if occ else None,
        "p50_ms": p50 * 1e3 if p50 is not None else None,
        "p99_ms": p99 * 1e3 if p99 is not None else None,
        "ok": ok,
        "rejected": snapshot_value(snap, "hvd_serve_requests_total",
                                   status="rejected") or 0.0,
        "expired": snapshot_value(snap, "hvd_serve_requests_total",
                                  status="expired") or 0.0,
        "hit_pct": (100.0 * (hits or 0.0) / lookups if lookups else None),
        "blocks": (f"{int(used)}/{int(pool)}"
                   if used is not None and pool is not None else None),
        "reuse": snapshot_value(snap, "hvd_serve_cache_reuse_total"),
        "qps_raw": (now, ok),
    }


def tune_row_from_snapshot(target: dict, snap: dict) -> dict:
    """One tune-view row from the hvd_tune_* gauge family."""
    def v(name):
        return snapshot_value(snap, name)

    phase = v("hvd_tune_phase")
    comp = v("hvd_tune_compression")
    obj = v("hvd_tune_objective_seconds")
    best = v("hvd_tune_best_objective_seconds")
    return {
        "rank": _rank_of(target, snap),
        "bucket_bytes": v("hvd_tune_bucket_bytes"),
        "fusion_mb": (v("hvd_tune_fusion_threshold_bytes") / (1 << 20)
                      if v("hvd_tune_fusion_threshold_bytes") is not None
                      else None),
        "cycle_ms": v("hvd_tune_cycle_time_ms"),
        "lane_bytes": v("hvd_tune_low_latency_threshold_bytes"),
        "ring_threshold_bytes": v("hvd_tune_ring_threshold_bytes"),
        "hierarchical": v("hvd_tune_hierarchical"),
        "small_tensor_algo": v("hvd_tune_small_tensor_algo"),
        "compression": (_TUNE_COMP.get(int(comp))
                        if comp is not None else None),
        "phase": (_TUNE_PHASES.get(int(phase))
                  if phase is not None else None),
        "objective_ms": obj * 1e3 if obj is not None else None,
        "best_ms": best * 1e3 if best is not None else None,
        "samples": v("hvd_tune_samples_total"),
    }


def admission_class_counters(snap: dict) -> Dict[str, Dict[str, float]]:
    """``{class: {"admitted": n, "shed": n}}`` from one snapshot — the
    per-class admit/shed families serve/admission.py exports."""
    out: Dict[str, Dict[str, float]] = {}
    for m in snap.get("metrics", []):
        field = {"hvd_serve_admit_total": "admitted",
                 "hvd_serve_shed_total": "shed"}.get(m.get("name"))
        if field is None:
            continue
        for s in m.get("samples", []):
            cls = s.get("labels", {}).get("class")
            if cls is None or "value" not in s:
                continue
            out.setdefault(cls, {"admitted": 0.0, "shed": 0.0})
            out[cls][field] += float(s["value"])
    return out


def autoscale_row_from_snapshot(target: dict, snap: dict) -> dict:
    """One autoscale-view row: the same WorkerSLO extraction the driver's
    policy loop uses, plus the admission counters."""
    from horovod_tpu.metrics import histogram_quantile, snapshot_histogram
    from horovod_tpu.runner.elastic.autoscaler import slo_headroom
    qd = snapshot_value(snap, "hvd_serve_queue_depth")
    lat = snapshot_histogram(snap, "hvd_serve_request_latency_seconds")
    p99 = histogram_quantile(lat, 0.99) if lat else None
    p99_ms = p99 * 1e3 if p99 is not None else None
    classes = admission_class_counters(snap)
    return {
        "rank": _rank_of(target, snap),
        "queue_depth": qd,
        "inflight": snapshot_value(snap, "hvd_serve_inflight"),
        "p99_ms": p99_ms,
        "headroom": slo_headroom(qd, p99_ms),
        "admitted": sum(c["admitted"] for c in classes.values())
        if classes else None,
        "shed": sum(c["shed"] for c in classes.values())
        if classes else None,
        "quota_shed": snapshot_value(snap, "hvd_serve_quota_shed_total"),
        "classes": classes,
    }


def render_autoscale(rows: List[dict], unreachable: int = 0,
                     title: str = "", status: Optional[dict] = None) -> str:
    lines = []
    if title:
        lines.append(title)
    if status is not None:
        age = status.get("age_seconds")
        lines.append(
            f"fleet={status.get('fleet', '-')} "
            f"last={status.get('action', '-')}"
            f"[{status.get('state', '-')}] "
            f"reason={status.get('reason') or '-'} "
            f"age={age if age is not None else '-'}s")
    else:
        lines.append(f"fleet={len(rows)} (no KV: last decision unknown — "
                     f"pass --kv for the autoscale/decision record)")
    lines.append(_AUTOSCALE_FMT.format(*AUTOSCALE_COLUMNS))
    classes: Dict[str, Dict[str, float]] = {}
    for r in rows:
        for cls, c in r.get("classes", {}).items():
            agg = classes.setdefault(cls, {"admitted": 0.0, "shed": 0.0})
            agg["admitted"] += c["admitted"]
            agg["shed"] += c["shed"]
        lines.append(_AUTOSCALE_FMT.format(
            r["rank"], _fmt(r["queue_depth"], "{:.0f}"),
            _fmt(r["inflight"], "{:.0f}"),
            _fmt(r["p99_ms"], "{:.2f}"),
            _fmt(r["headroom"], "{:+.2f}"),
            _fmt(r["admitted"], "{:.0f}"), _fmt(r["shed"], "{:.0f}"),
            _fmt(r["quota_shed"], "{:.0f}")))
    if classes:
        lines.append("classes (admit/shed): " + "  ".join(
            f"{cls} {int(c['admitted'])}/{int(c['shed'])}"
            for cls, c in sorted(classes.items())))
    if unreachable:
        lines.append(f"({unreachable} target(s) unreachable)")
    return "\n".join(lines)


def _fmt_bucket(v) -> str:
    if v is None:
        return "-"
    v = int(v)
    if v <= 0:
        return "off"
    if v >= 1 << 20:
        return f"{v / (1 << 20):.0f}M"
    return f"{v / 1024:.0f}K"


def _fmt_algo(row: dict) -> str:
    """The routing decision as one cell: "<flat|hier>+<star|rd>@<ring>"
    — e.g. "hier+rd@1M". "-" when the routing gauges are absent (an older
    tuner or a space without the routing dimensions)."""
    hier = row.get("hierarchical")
    small = row.get("small_tensor_algo")
    ring = row.get("ring_threshold_bytes")
    if hier is None and small is None and ring is None:
        return "-"
    level = "hier" if hier else "flat"
    route = _TUNE_SMALL_ALGO.get(int(small), "star") \
        if small is not None else "star"
    return f"{level}+{route}@{_fmt_bucket(ring)}"


def render_tune(rows: List[dict], unreachable: int = 0,
                title: str = "") -> str:
    lines = []
    if title:
        lines.append(title)
    lines.append(_TUNE_FMT.format(*TUNE_COLUMNS))
    for r in rows:
        lines.append(_TUNE_FMT.format(
            r["rank"], _fmt_bucket(r["bucket_bytes"]),
            _fmt(r["fusion_mb"], "{:.0f}"),
            _fmt(r["cycle_ms"], "{:.2f}"),
            _fmt_bucket(r["lane_bytes"]),
            _fmt_algo(r),
            r["compression"] or "-", r["phase"] or "-",
            _fmt(r["objective_ms"], "{:.2f}"),
            _fmt(r["best_ms"], "{:.2f}"),
            _fmt(r["samples"], "{:.0f}")))
    if unreachable:
        lines.append(f"({unreachable} target(s) unreachable)")
    return "\n".join(lines)


def render_serving(rows: List[dict], unreachable: int = 0,
                   title: str = "") -> str:
    lines = []
    if title:
        lines.append(title)
    lines.append(_SERVING_FMT.format(*SERVING_COLUMNS))
    for r in rows:
        lines.append(_SERVING_FMT.format(
            r["rank"], _fmt(r["qps"], "{:.1f}"),
            _fmt(r["queue_depth"], "{:.0f}"),
            _fmt(r["inflight"], "{:.0f}"),
            _fmt(r["occupancy"], "{:.1f}"),
            _fmt(r["p50_ms"], "{:.2f}"), _fmt(r["p99_ms"], "{:.2f}"),
            _fmt(r["ok"], "{:.0f}"), _fmt(r["rejected"], "{:.0f}"),
            _fmt(r["expired"], "{:.0f}"),
            _fmt(r["hit_pct"], "{:.1f}"),
            r["blocks"] or "-",
            _fmt(r["reuse"], "{:.0f}")))
    if unreachable:
        lines.append(f"({unreachable} target(s) unreachable)")
    return "\n".join(lines)


def _fmt(v, pattern="{:.1f}") -> str:
    return pattern.format(v) if v is not None else "-"


def _gauge_mean(merged: dict, name: str) -> Optional[float]:
    """Mean over a merged snapshot's per-rank gauge vector (the
    aggregator keeps gauges as one sample per rank — a mean is the only
    host-level reading that makes sense for ratios)."""
    values = []
    for m in merged.get("metrics", []):
        if m.get("name") != name:
            continue
        values.extend(float(s["value"]) for s in m.get("samples", [])
                      if "value" in s)
    return sum(values) / len(values) if values else None


def host_row_from_agg(target: dict, payload: dict,
                      prev_steps: Optional[Tuple[int, float]],
                      stale_after: float) -> dict:
    """One host-rollup row from an /agg.json payload. ``prev_steps`` is
    the host-merged step histogram's (count, sum) at the previous
    refresh; None (--once) shows the lifetime mean."""
    from horovod_tpu.metrics import histogram_quantile, snapshot_histogram
    merged = payload.get("merged", {})
    stats = step_stats(merged)
    step_ms = None
    if stats is not None:
        count, total = stats
        if prev_steps is not None and count > prev_steps[0]:
            step_ms = 1e3 * (total - prev_steps[1]) / (count - prev_steps[0])
        elif prev_steps is None and count:
            step_ms = 1e3 * total / count
    hist = snapshot_histogram(merged, STEP_SECONDS)
    p99 = histogram_quantile(hist, 0.99) if hist else None
    exp = _gauge_mean(merged, "hvd_step_exposed_comm_ratio")
    stall = _gauge_mean(merged, "hvd_step_stall_seconds")
    step_last = _gauge_mean(merged, "hvd_step_seconds_last")
    qd = snapshot_value(merged, "hvd_engine_queue_depth")
    age = payload.get("age_seconds")
    return {
        "host": payload.get("host") or target.get("host") or target["addr"],
        "ranks": len(payload.get("ranks", {})),
        "step_ms": step_ms,
        "p99_ms": p99 * 1e3 if p99 is not None else None,
        "exposed_pct": 100.0 * exp if exp is not None else None,
        "stall_pct": (100.0 * stall / step_last
                      if stall is not None and step_last else None),
        "queue_depth": qd,
        "anomalies": snapshot_value(merged, "hvd_step_anomaly_total") or 0.0,
        "scrape_errors": payload.get("scrape_errors"),
        "age_seconds": age,
        "stale": age is not None and float(age) > stale_after,
        "steps_raw": stats,
    }


def render_rollup(rows: List[dict], unreachable: int = 0,
                  title: str = "", stale_after: float = 0.0) -> str:
    lines = []
    if title:
        lines.append(title)
    lines.append(_ROLLUP_FMT.format(*ROLLUP_COLUMNS))
    stale = 0
    for r in rows:
        stale += 1 if r["stale"] else 0
        age = _fmt(r["age_seconds"], "{:.1f}")
        lines.append(_ROLLUP_FMT.format(
            r["host"][:12], r["ranks"],
            _fmt(r["step_ms"], "{:.2f}"), _fmt(r["p99_ms"], "{:.2f}"),
            _fmt(r["exposed_pct"]), _fmt(r["stall_pct"]),
            _fmt(r["queue_depth"], "{:.0f}"),
            _fmt(r["anomalies"], "{:.0f}"),
            _fmt(r["scrape_errors"], "{:.0f}"),
            age + ("!" if r["stale"] else "")))
    if stale:
        lines.append(f"*** STALE DATA: {stale} aggregator(s) older than "
                     f"{stale_after:.0f}s (rows marked '!') — the driver "
                     f"is direct-scraping those hosts ***")
    if unreachable:
        lines.append(f"({unreachable} aggregator(s) unreachable)")
    return "\n".join(lines)


def render(rows: List[dict], unreachable: int = 0,
           title: str = "") -> str:
    """The table, straggler scores filled in from the rows' window step
    times (leave-one-out skew — the elastic driver's math)."""
    times = {i: r["step_seconds"] for i, r in enumerate(rows)
             if r["step_seconds"]}
    det = StragglerDetector(windows=1)
    det.update(times)
    lines = []
    if title:
        lines.append(title)
    lines.append(_FMT.format(*COLUMNS))
    for i, r in enumerate(rows):
        score = det.last_scores.get(i)
        lines.append(_FMT.format(
            r["rank"], _fmt(r["step_ms"], "{:.2f}"),
            _fmt(r["exposed_pct"]), _fmt(r["stall_pct"]),
            _fmt(r["cache_pct"]), _fmt(r["fuse"], "{:.1f}"),
            _fmt(r["queue_depth"], "{:.0f}"),
            _fmt(score, "{:+.1f}"), _fmt(r["anomalies"], "{:.0f}")))
    if unreachable:
        lines.append(f"({unreachable} target(s) unreachable)")
    return "\n".join(lines)


class TopState:
    """Scrape-window state for the live view (previous step-histogram
    totals per target, so STEP ms is a window mean, not a lifetime one).

    Control-plane outages must not take the view down with them: when no
    target answers at all (driver/KV dead, workers mid-restart) the last
    successful rows are re-shown with a STALE banner carrying the
    last-scrape age, and the view recovers by itself once any scrape
    succeeds again — ``stale_age_seconds`` is None while fresh."""

    def __init__(self, targets: List[dict], serving: bool = False,
                 tune: bool = False, autoscale: bool = False,
                 kv: Optional[Tuple[str, int]] = None,
                 rollup: bool = False,
                 kv_endpoints: Optional[List[str]] = None):
        self.targets = targets
        self.serving = serving
        self.tune = tune
        self.autoscale = autoscale
        self.rollup = rollup
        self.stale_after = env_float("HOROVOD_AGG_STALE_SECONDS")
        self._kv = kv
        self.kv_endpoints = kv_endpoints
        self._prev: Dict[int, Tuple] = {}
        self._last_rows: List[dict] = []
        self._last_scrape: Optional[float] = None  # monotonic
        self.stale_age_seconds: Optional[float] = None

    def _refresh_rollup(self, window: bool) -> Tuple[List[dict], int]:
        """Host-rollup pass: H /agg.json scrapes instead of N
        /metrics.json ones (``self.targets`` holds aggregator
        endpoints)."""
        rows, unreachable = [], 0
        for i, t in enumerate(self.targets):
            payload = scrape_agg(t)
            if payload is None:
                unreachable += 1
                continue
            row = host_row_from_agg(
                t, payload, self._prev.get(i) if window else None,
                self.stale_after)
            if row["steps_raw"] is not None:
                self._prev[i] = row["steps_raw"]
            rows.append(row)
        rows.sort(key=lambda r: r["host"])
        return rows, unreachable

    def refresh(self, window: bool = True) -> Tuple[List[dict], int]:
        if self.rollup:
            rows, unreachable = self._refresh_rollup(window)
            if rows:
                self._last_rows = rows
                self._last_scrape = time.monotonic()
                self.stale_age_seconds = None
            elif self._last_scrape is not None:
                self.stale_age_seconds = \
                    time.monotonic() - self._last_scrape
                return list(self._last_rows), unreachable
            return rows, unreachable
        rows, unreachable = [], 0
        for i, t in enumerate(self.targets):
            snap = scrape_target(t)
            if snap is None:
                unreachable += 1
                continue
            prev = self._prev.get(i) if window else None
            if self.autoscale:
                row = autoscale_row_from_snapshot(t, snap)
            elif self.tune:
                row = tune_row_from_snapshot(t, snap)
            elif self.serving:
                row = serving_row_from_snapshot(t, snap, prev)
                self._prev[i] = row["qps_raw"]
            else:
                row = row_from_snapshot(t, snap, prev)
                if row["steps_raw"] is not None:
                    self._prev[i] = row["steps_raw"]
            rows.append(row)
        rows.sort(key=lambda r: (len(r["rank"]), r["rank"]))
        if rows:
            self._last_rows = rows
            self._last_scrape = time.monotonic()
            self.stale_age_seconds = None
        elif self._last_scrape is not None:
            # total outage: show the last good table, age-stamped, instead
            # of a blank screen or a crash — and keep polling
            self.stale_age_seconds = time.monotonic() - self._last_scrape
            return list(self._last_rows), unreachable
        return rows, unreachable

    def autoscale_status(self) -> Optional[dict]:
        """The KV's autoscale/decision record (banner), when reachable."""
        if self._kv is None:
            return None
        try:
            from horovod_tpu.runner.elastic.autoscaler import \
                autoscale_status
            from horovod_tpu.runner.http_kv import KVClient
            client = KVClient(*self._kv)
            return autoscale_status(
                lambda key: client.get_json(key, timeout=2.0))
        except Exception:  # noqa: BLE001 — KV outage: banner only
            return None

    def render(self, rows: List[dict], unreachable: int,
               title: str) -> str:
        if self.rollup:
            text = render_rollup(rows, unreachable, title,
                                 stale_after=self.stale_after)
        elif self.autoscale:
            text = render_autoscale(rows, unreachable, title,
                                    status=self.autoscale_status())
        elif self.tune:
            text = render_tune(rows, unreachable, title)
        elif self.serving:
            text = render_serving(rows, unreachable, title)
        else:
            text = render(rows, unreachable, title)
        if self.stale_age_seconds is not None:
            banner = (f"*** STALE DATA: no target reachable "
                      f"(driver/KV down?) — showing last scrape from "
                      f"{self.stale_age_seconds:.0f}s ago ***")
            text = banner + "\n" + text
        if self.kv_endpoints:
            try:
                text = render_kv_banner(
                    kv_health(self.kv_endpoints)) + "\n" + text
            except Exception:  # noqa: BLE001 — banner is best-effort
                pass
        journal_dir = env_str("HOROVOD_JOURNAL_DIR")
        if journal_dir:
            try:
                doctor_line = render_doctor_banner(journal_dir)
                if doctor_line:
                    text = doctor_line + "\n" + text
            except Exception:  # noqa: BLE001 — banner is best-effort
                pass
        return text


def _title(n_rows: int, n_targets: int, unit: str = "ranks") -> str:
    return (f"hvd-top  {time.strftime('%H:%M:%S')}  "
            f"{n_rows}/{n_targets} {unit} reporting  (q to quit)")


def _state_title(state: TopState, n_rows: int) -> str:
    return _title(n_rows, len(state.targets),
                  "hosts" if state.rollup else "ranks")


def _loop_plain(state: TopState, interval: float):
    while True:
        rows, unreachable = state.refresh()
        sys.stdout.write("\x1b[2J\x1b[H" if sys.stdout.isatty() else "")
        print(state.render(rows, unreachable,
                           _state_title(state, len(rows))))
        sys.stdout.flush()
        time.sleep(interval)


def _loop_curses(scr, state: TopState, interval: float):
    import curses
    curses.curs_set(0)
    scr.nodelay(True)
    while True:
        rows, unreachable = state.refresh()
        scr.erase()
        text = state.render(rows, unreachable,
                            _state_title(state, len(rows)))
        maxy, maxx = scr.getmaxyx()
        for y, line in enumerate(text.splitlines()[:maxy - 1]):
            scr.addnstr(y, 0, line, maxx - 1)
        scr.refresh()
        deadline = time.monotonic() + interval
        while time.monotonic() < deadline:
            if scr.getch() in (ord("q"), ord("Q")):
                return
            time.sleep(0.05)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvd-top",
        description="live per-rank cluster view over /metrics.json "
                    "endpoints")
    parser.add_argument("--targets",
                        help="comma-separated host:port metrics endpoints")
    parser.add_argument("--kv", help="rendezvous KV host:port publishing "
                                     "the metrics_targets key; a comma-"
                                     "separated list names the whole "
                                     "replica set (adds the KV health "
                                     "banner)")
    parser.add_argument("--once", action="store_true",
                        help="print one snapshot and exit")
    parser.add_argument("--interval", type=float, default=None,
                        help="refresh seconds (default "
                             "HOROVOD_TOP_INTERVAL)")
    parser.add_argument("--plain", action="store_true",
                        help="no curses, dumb redraw loop")
    parser.add_argument("--serving", action="store_true",
                        help="serving view: per-rank QPS, queue depth, "
                             "batch occupancy, p50/p99 latency")
    parser.add_argument("--tune", action="store_true",
                        help="tuner view: current bucket/fusion/cycle/"
                             "express-lane knobs, search phase, objective "
                             "trend (hvd_tune_* gauges)")
    parser.add_argument("--autoscale", action="store_true",
                        help="autoscale view: fleet size + last decision "
                             "(KV autoscale/decision record), per-rank "
                             "SLO headroom, per-class admit/shed "
                             "counters")
    parser.add_argument("--rollup", action="store_true",
                        help="force the per-host aggregator rollup view "
                             "even below HOROVOD_TOP_ROLLUP_RANKS")
    parser.add_argument("--no-rollup", action="store_true",
                        help="force per-rank rows even above "
                             "HOROVOD_TOP_ROLLUP_RANKS")
    parser.add_argument("--rank", type=int, default=None,
                        help="drill down to one rank's per-rank row, "
                             "resolved through the aggregator tier")
    args = parser.parse_args(argv)
    if sum((args.serving, args.tune, args.autoscale)) > 1:
        print("hvd-top: --serving, --tune and --autoscale are mutually "
              "exclusive", file=sys.stderr)
        return 2
    if args.rollup and args.no_rollup:
        print("hvd-top: --rollup and --no-rollup are mutually exclusive",
              file=sys.stderr)
        return 2

    try:
        targets = discover_targets(args)
        kv = _kv_coords(args)
    except ValueError as e:
        print(f"hvd-top: {e}", file=sys.stderr)
        return 2

    # the tiered plane: per-host aggregator endpoints, when the driver
    # publishes them (rollup + --rank drill-down both ride on these)
    agg_targets: List[dict] = []
    if not (args.serving or args.tune or args.autoscale or
            args.no_rollup or args.targets):
        try:
            agg_targets = discover_agg_targets(args)
        except Exception:  # noqa: BLE001 — KV outage: per-rank fallback
            agg_targets = []
    if args.rank is not None:
        t = resolve_rank_target(agg_targets, targets, args.rank)
        if t is None:
            print(f"hvd-top: rank {args.rank} not found via the "
                  f"aggregator tier or the per-rank target list",
                  file=sys.stderr)
            return 2
        targets = [t]
    use_rollup = (args.rank is None and bool(agg_targets) and
                  (args.rollup or not targets or
                   len(targets) > env_int("HOROVOD_TOP_ROLLUP_RANKS")))
    if use_rollup:
        targets = agg_targets

    kv_endpoints = _kv_endpoints(args)
    if not targets:
        print("hvd-top: no targets (pass --targets host:port, point --kv "
              "at the rendezvous KV, or set HOROVOD_METRICS_PORT)",
              file=sys.stderr)
        return 2
    state = TopState(targets, serving=args.serving, tune=args.tune,
                     autoscale=args.autoscale, kv=kv, rollup=use_rollup,
                     kv_endpoints=kv_endpoints)

    if args.once:
        rows, unreachable = state.refresh(window=False)
        if kv_endpoints:
            health = kv_health(kv_endpoints)
            if health["leader"] is None:
                print(f"hvd-top: control-plane suspect: no KV leader "
                      f"reachable among {','.join(kv_endpoints)} "
                      f"({health['up']}/{health['total']} replicas up)",
                      file=sys.stderr)
                return 1
        if not rows:
            print(f"hvd-top: none of {len(targets)} target(s) answered "
                  f"(workers down, or the driver/KV publishing "
                  f"metrics_targets is unreachable)",
                  file=sys.stderr)
            return 1
        print(state.render(rows, unreachable, _state_title(state,
                                                           len(rows))))
        return 0

    interval = args.interval if args.interval is not None \
        else env_float("HOROVOD_TOP_INTERVAL")
    use_curses = not args.plain and sys.stdout.isatty()
    if use_curses:
        try:
            import curses
        except ImportError:
            use_curses = False
    try:
        if use_curses:
            curses.wrapper(lambda scr: _loop_curses(scr, state, interval))
        else:
            _loop_plain(state, interval)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
