"""``hvd-doctor`` — offline incident analyzer over the fused event plane.

The repo emits five artifact families during ordinary operation: the
durable event journal (:mod:`horovod_tpu.common.journal`), per-shard KV
WALs, flight-recorder dumps, request-trace rings, and the metrics plane.
When an incident happens (a worker SIGKILLed mid-step, a KV leader
election under a half-finished resize, a drain racing a kill), the
evidence is spread across all of them. This module fuses journals from
every host with the KV WALs and flight dumps into ONE causally-ordered
timeline, runs a detector pipeline over it, and prints a ranked
**verdict**: root cause, the evidence events by id, the blast radius,
and a remediation hint.

Ordering rules (the tentpole's contract):

- Control-plane events are **fenced**: they carry ``control_epoch`` and
  ``generation``, which only move forward (the conformance auditors
  enforce exactly that). The timeline's primary order is
  ``(control_epoch, generation)`` — carried forward per writer stream
  for events between fenced ones — so a stale epoch's events sort
  before the election that fenced them regardless of clock skew.
- Within a fence bucket, wall clocks order cross-writer events and the
  per-writer ``seq`` breaks ties (journal appends are monotonic per
  writer by construction).
- Per-rank flight events have no trustworthy wall clock; they are
  aligned across ranks with the PR-5 CYCLE anchor method
  (:func:`horovod_tpu.profiler.flight.align_clocks`) and anchored to
  wall time by each dump's ``dump_unix_us``.

Run it as ``hvd-doctor <dir>`` (or ``python -m horovod_tpu.obs.doctor``,
or ``make doctor``) over a soak artifact directory — the same layout
``make conformance`` replays: ``journal/`` (or loose ``journal_*.log``),
``kv/`` and ``flight/`` subdirectories are discovered automatically.
Every run also writes ``doctor_verdict.json`` next to the journal so
``hvd-top`` can surface the newest verdict's age + incident count in its
banner, and ``--perfetto OUT`` exports the fused timeline through the
PR-5 ``trace_merge`` writer.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from horovod_tpu.common import journal

VERDICT_FILE = "doctor_verdict.json"

# Shed-storm clustering: this many sheds inside the window is a storm,
# not backpressure doing its job.
SHED_STORM_MIN = 10
SHED_STORM_WINDOW_SEC = 10.0

_DRAIN_EVENTS = ("preempt_drain", "admin_drain", "drain_announce")
_SHED_EVENTS = ("shed", "request_rejected", "request_expired")


# ===========================================================================
# Timeline construction
# ===========================================================================

def _journal_timeline(journal_dir) -> List[dict]:
    out = []
    for rec in journal.iter_journal(journal_dir):
        ev = dict(rec)
        ev["source"] = "journal"
        ev["writer"] = f"{rec.get('host')}:{rec.get('pid')}"
        out.append(ev)
    return out


def _kv_timeline(kv_dir) -> List[dict]:
    """KV WAL ops as timeline events (read-only; one stream per shard).
    The op-level ``"e"`` stamp is the epoch claim, the decoded value's
    ``ts``/``generation`` fields supply wall clock and generation where
    the family records them."""
    from horovod_tpu.verify import conformance
    kv_dir = Path(kv_dir)
    wal_files = {"core": "wal.log"}
    for f in sorted(kv_dir.glob("wal-*.log")):
        wal_files[f.name[len("wal-"):-len(".log")]] = f.name
    out: List[dict] = []
    for shard, wal_file in wal_files.items():
        for i, op in enumerate(conformance.iter_wal_ops(kv_dir, wal_file)):
            val = conformance._decoded_value(op) \
                if op.get("op") == "put" else None
            ev = {
                "id": f"kvwal:{shard}:{op.get('s', i)}",
                "source": "kv_wal",
                "writer": f"kvwal:{shard}",
                "component": "kv_wal",
                "event": f"{op.get('op', '?')} {op.get('k', '')}",
                "seq": op.get("s", i),
            }
            if op.get("e") is not None:
                ev["control_epoch"] = op["e"]
            if isinstance(val, dict):
                if "generation" in val:
                    ev["generation"] = val["generation"]
                if "ts" in val:
                    try:
                        ev["t_wall"] = float(val["ts"])
                    except (TypeError, ValueError):
                        pass
                ev["detail"] = {k: v for k, v in val.items()
                                if k not in ("ts",)}
            out.append(ev)
    return out


def _flight_timeline(dumps: Dict[int, dict]) -> List[dict]:
    """Per-rank flight events worth fusing (DESYNC + dump triggers),
    CYCLE-aligned and wall-anchored by each dump's ``dump_unix_us``."""
    from horovod_tpu.profiler.flight import align_clocks
    if not dumps:
        return []
    offsets = align_clocks(dumps)
    # wall anchor: pick one rank's (dump wall time, last aligned mono)
    # pair and place every aligned mono timestamp relative to it
    anchor_rank = sorted(dumps)[0]
    anchor_wall = float(dumps[anchor_rank].get("dump_unix_us", 0)) / 1e6
    anchor_mono = max((float(e.get("ts_us", 0))
                       for e in dumps[anchor_rank].get("events", [])),
                      default=0.0) + offsets.get(anchor_rank, 0.0)
    out: List[dict] = []
    for r, d in sorted(dumps.items()):
        for i, e in enumerate(d.get("events", [])):
            phase = e.get("phase")
            if phase not in ("DESYNC", "DUMP"):
                continue
            aligned = float(e.get("ts_us", 0)) + offsets.get(r, 0.0)
            out.append({
                "id": f"flight:{r}:{i}",
                "source": "flight",
                "writer": f"flight:{r}",
                "component": "flight",
                "event": f"{phase} {e.get('name', '')}".strip(),
                "rank": r,
                "seq": i,
                "t_wall": anchor_wall + (aligned - anchor_mono) / 1e6
                if anchor_wall else None,
            })
    return out


def order_events(events: List[dict]) -> List[dict]:
    """Causal order: (control_epoch, generation) fence buckets first —
    carried forward per writer stream so unfenced events ride their
    stream's last-known fence — then wall clock, then (writer, seq)."""
    by_writer: Dict[str, List[dict]] = {}
    for ev in events:
        by_writer.setdefault(ev.get("writer", "?"), []).append(ev)
    for stream in by_writer.values():
        stream.sort(key=lambda e: (e.get("seq") if isinstance(
            e.get("seq"), (int, float)) else 0))
        epoch, gen = -1, -1
        for ev in stream:
            if isinstance(ev.get("control_epoch"), (int, float)):
                epoch = max(epoch, int(ev["control_epoch"]))
            if isinstance(ev.get("generation"), (int, float)):
                gen = max(gen, int(ev["generation"]))
            ev["_ek"], ev["_gk"] = epoch, gen

    def key(ev):
        tw = ev.get("t_wall")
        return (ev["_ek"], ev["_gk"],
                float(tw) if tw is not None else 0.0,
                str(ev.get("writer", "")),
                ev.get("seq") if isinstance(ev.get("seq"),
                                            (int, float)) else 0)
    out = sorted(events, key=key)
    for ev in out:
        ev.pop("_ek", None)
        ev.pop("_gk", None)
    return out


def _discover_dirs(path) -> Tuple[Optional[Path], List[Path], List[Path]]:
    """(journal_dir, kv_dirs, flight_dirs) under a soak artifact root.
    Loose ``journal_*.log`` files in the root count as the journal."""
    path = Path(path)
    journal_dir = None
    for cand in (path / "journal", path):
        if sorted(cand.glob("journal_*.log")):
            journal_dir = cand
            break
    kv_dirs, seen = [], set()
    for d in [path, path / "kv", *sorted(path.glob("**/"))]:
        d = d.resolve()
        if d not in seen and ((d / "wal.log").exists()
                              or sorted(d.glob("wal-*.log"))):
            seen.add(d)
            kv_dirs.append(d)
    flight_dirs = sorted({f.parent
                          for f in path.glob("**/flight_rank*.json")})
    return journal_dir, kv_dirs, flight_dirs


def build_timeline(path, journal_dir=None, kv_dir=None,
                   flight_dir=None) -> dict:
    """The analysis context: fused ordered events + the per-family
    artifacts the detectors lean on (flight analyzer verdict,
    conformance divergences)."""
    auto_journal, auto_kv, auto_flight = _discover_dirs(path)
    journal_dir = Path(journal_dir) if journal_dir else auto_journal
    kv_dirs = [Path(kv_dir)] if kv_dir else auto_kv
    flight_dirs = [Path(flight_dir)] if flight_dir else auto_flight

    events: List[dict] = []
    if journal_dir is not None:
        events += _journal_timeline(journal_dir)
    for d in kv_dirs:
        events += _kv_timeline(d)

    from horovod_tpu.profiler import flight
    dumps: Dict[int, dict] = {}
    for d in flight_dirs:
        dumps.update(flight.load_dumps(d))
    flight_verdict = flight.analyze(dumps) if dumps else None
    events += _flight_timeline(dumps)

    divergences: List[str] = []
    from horovod_tpu.verify import conformance
    for d in kv_dirs:
        divergences += conformance.check_kv_wal(d)
    if journal_dir is not None:
        divergences += conformance.check_journal(journal_dir)

    return {
        "path": str(path),
        "journal_dir": str(journal_dir) if journal_dir else None,
        "kv_dirs": [str(d) for d in kv_dirs],
        "flight_dirs": [str(d) for d in flight_dirs],
        "events": order_events(events),
        "flight_dumps": dumps,
        "flight_verdict": flight_verdict,
        "divergences": divergences,
    }


# ===========================================================================
# Detector pipeline
# ===========================================================================

def _incident(cause: str, severity: int, title: str, root_cause: str,
              evidence: List[str], blast_radius: str,
              remediation: str, **detail) -> dict:
    inc = {"cause": cause, "severity": int(severity), "title": title,
           "root_cause": root_cause,
           "evidence": [e for e in evidence if e][:16],
           "blast_radius": blast_radius, "remediation": remediation}
    if detail:
        inc["detail"] = detail
    return inc


def _slot(ev: dict) -> Optional[Tuple[str, object]]:
    d = ev.get("detail") or {}
    host, lr = d.get("host"), d.get("local_rank")
    if host is None:
        return None
    return (str(host), lr)


def detect_dead_rank(ctx) -> List[dict]:
    """Worker death that no drain explains: SIGKILL/OOM/crash mid-step.
    Flight-analyzer dead ranks corroborate when dumps are present."""
    out = []
    drained: set = set()
    for ev in ctx["events"]:
        if ev.get("event") in _DRAIN_EVENTS:
            s = _slot(ev)
            if s:
                drained.add(s)
        if ev.get("event") == "worker_exit" and \
                (ev.get("detail") or {}).get("reason") == "failure":
            s = _slot(ev)
            if s in drained:
                continue  # the drain-race detector owns this one
            d = ev.get("detail") or {}
            fl = ctx.get("flight_verdict") or {}
            evidence = [ev.get("id")]
            corroboration = ""
            if fl.get("dead_ranks"):
                evidence += [f"flight:{r}" for r in fl["dead_ranks"]]
                corroboration = (" — flight analyzer confirms rank(s) "
                                 f"{fl['dead_ranks']} left no dump")
            if fl.get("in_flight"):
                tensors = [x.get("tensor") for x in fl["in_flight"][:3]]
                corroboration += (f"; collective(s) {tensors} were "
                                  "in flight")
            out.append(_incident(
                "dead_rank", 100, "worker died mid-step",
                f"worker {s[0]}/{s[1]} exited with code "
                f"{d.get('exit_code')} with no drain announced (killed: "
                f"SIGKILL/OOM/crash){corroboration}",
                evidence,
                f"generation {ev.get('generation')} torn down; every "
                "surviving rank re-rendezvoused at the next generation",
                "check the host's OOM killer / preemption logs; the "
                "driver respawns the slot — recurring deaths on one "
                "host end in a blacklist",
                host=s[0], local_rank=s[1],
                exit_code=d.get("exit_code")))
    return out


def detect_desync(ctx) -> List[dict]:
    fl = ctx.get("flight_verdict") or {}
    desync_events = [e for e in ctx["events"]
                     if e.get("source") == "flight"
                     and e.get("event", "").startswith("DESYNC")]
    journal_desync = [e for e in ctx["events"]
                      if e.get("event") == "flight_verdict"
                      and (e.get("detail") or {}).get("desync")]
    if not (fl.get("desync") or desync_events or journal_desync):
        return []
    return [_incident(
        "desync", 95, "cross-rank collective desync",
        "ranks submitted mismatched collectives under one name "
        "(signature/exec-order divergence) — a framework-level bug, "
        "not an infrastructure failure",
        [e.get("id") for e in desync_events + journal_desync] or
        ["flight-analyzer"],
        "the whole job: results past the divergence are suspect",
        "inspect the flight dumps' DESYNC records "
        "(hvd-flight-analyze) and bisect the model change that made "
        "rank programs diverge")]


def detect_drain_race(ctx) -> List[dict]:
    """A drain that lost its race: announced, but the worker died (or a
    second drain piled on) before the handoff finalized."""
    out = []
    drains: Dict[Tuple[str, object], dict] = {}
    kinds: Dict[Tuple[str, object], set] = {}
    finalized: set = set()
    for ev in ctx["events"]:
        s = _slot(ev)
        if ev.get("event") in _DRAIN_EVENTS and s:
            drains.setdefault(s, ev)
            kinds.setdefault(s, set()).add(ev["event"])
        if ev.get("event") == "worker_exit" and s:
            reason = (ev.get("detail") or {}).get("reason")
            if reason == "drained":
                finalized.add(s)
            if reason == "failure" and s in drains:
                out.append(_incident(
                    "drain_race", 80, "drain lost its race",
                    f"worker {s[0]}/{s[1]} announced a drain "
                    f"(event {drains[s].get('id')}) but died (exit "
                    f"{(ev.get('detail') or {}).get('exit_code')}) "
                    "before the handoff completed — the preemption "
                    "window was shorter than the drain",
                    [drains[s].get("id"), ev.get("id")],
                    "the slot's shard handoff was lost; the next "
                    "generation re-materialized its state",
                    "raise the preemption notice lead time or shrink "
                    "commit intervals so handoffs beat the reaper",
                    host=s[0], local_rank=s[1]))
    for s, ks in kinds.items():
        if "admin_drain" in ks and len(ks) > 1 and s not in finalized:
            out.append(_incident(
                "drain_race", 78, "double drain on one slot",
                f"slot {s[0]}/{s[1]} was drained by the autoscaler AND "
                "announced its own preemption drain — the second "
                "notice force-exits the worker, dropping acked work",
                [drains[s].get("id")],
                f"slot {s[0]}/{s[1]}'s in-flight requests",
                "the autoscaler must skip already-draining victims "
                "(AutoscaleSpec's victim_draining mutant pins this)",
                host=s[0], local_rank=s[1]))
    return out


def detect_split_brain(ctx) -> List[dict]:
    fenced = [e for e in ctx["events"]
              if e.get("event") == "stale_epoch_rejected"]
    self_fences = [e for e in ctx["events"]
                   if e.get("event") == "self_fence"]
    wal_split = [d for d in ctx.get("divergences", [])
                 if "split-brain" in d]
    out = []
    if fenced:
        offers = sorted({(e.get("detail") or {}).get("offered")
                         for e in fenced if e.get("detail")})
        current = max((e.get("control_epoch") or 0) for e in fenced)
        out.append(_incident(
            "split_brain_attempt", 85,
            "stale-epoch rival driver fenced",
            f"a fenced-out driver (epoch(s) {offers}) kept mutating "
            f"after epoch {current} was claimed — a rival/zombie "
            "incarnation; every attempt was rejected with 409",
            [e.get("id") for e in fenced],
            "none: fencing held, no stale mutation landed"
            if not wal_split else
            f"WAL audit found {len(wal_split)} stale mutation(s) that "
            "LANDED — state may be corrupt",
            "verify the old driver process is dead; if the WAL audit "
            "reports landed stale writes, restore from the last clean "
            "snapshot", rejections=len(fenced)))
    elif wal_split:
        out.append(_incident(
            "split_brain_attempt", 92, "split-brain mutation landed",
            "the KV WAL audit found mutations claiming a regressed "
            "control epoch — a stale driver's write was admitted",
            [], "control-plane state past the regression is suspect",
            "treat the KV as corrupt: re-seed from the last snapshot "
            "preceding the regression", divergences=wal_split[:4]))
    if self_fences:
        out.append(_incident(
            "split_brain_attempt", 70, "KV leader self-fenced",
            "a KV replica leader lost its majority/lease and stepped "
            "down rather than serve a minority partition",
            [e.get("id") for e in self_fences],
            "writes paused for one election round",
            "expected behavior under partition; investigate the "
            "network if it recurs"))
    return out


def detect_kv_leader_failover(ctx) -> List[dict]:
    elections = [e for e in ctx["events"]
                 if e.get("event") == "elected_leader"]
    respawns = [e for e in ctx["events"]
                if e.get("event") == "kv_replica_respawn"]
    if len(elections) < 2 and not respawns:
        return []  # a single election is just startup
    # was a resize/autoscale decision in flight across the failover?
    last_election_epoch = max((e.get("control_epoch") or 0)
                              for e in elections) if elections else None
    decides = [e for e in ctx["events"]
               if e.get("event") in ("autoscale_decide",
                                     "autoscale_resize",
                                     "autoscale_drain")]
    acks = [e for e in ctx["events"] if e.get("event") == "autoscale_ack"]
    acked = {(e.get("detail") or {}).get("seq") for e in acks}
    in_flight = [e for e in decides
                 if (e.get("detail") or {}).get("seq") not in acked]
    mid_resize = ""
    if in_flight:
        mid_resize = (" while autoscale decision seq "
                      f"{(in_flight[-1].get('detail') or {}).get('seq')} "
                      f"({(in_flight[-1].get('detail') or {}).get('action')}) "
                      "was between decide and ack")
    return [_incident(
        "kv_leader_failover", 90, "KV leader failover" +
        (" mid-resize" if in_flight else ""),
        f"the KV leader died and a successor was elected"
        f"{' (epoch ' + str(last_election_epoch) + ')' if last_election_epoch else ''}"
        f"{mid_resize}; majority-acked state survived by the election "
        "rule",
        [e.get("id") for e in respawns + elections],
        "control-plane writes stalled for one election; any in-flight "
        "resize resumed from its KV decision record",
        "nothing if it happened once (this is the design working); "
        "recurring leader deaths mean the replica host is sick",
        elections=len(elections), respawns=len(respawns),
        resize_in_flight=bool(in_flight))]


def detect_headless_outage(ctx) -> List[dict]:
    crashes = [e for e in ctx["events"]
               if e.get("event") == "driver_crash"]
    recoveries = [e for e in ctx["events"]
                  if e.get("event") == "driver_recovered"]
    exhausted = [e for e in ctx["events"]
                 if e.get("event") == "restart_limit_exhausted"]
    entered = [e for e in ctx["events"]
               if e.get("event") == "headless_entered"]
    exited = [e for e in ctx["events"]
              if e.get("event") == "headless_exited"]
    aborts = [e for e in ctx["events"]
              if e.get("event") == "headless_abort"]
    out = []
    unhealed = exhausted or aborts or \
        (entered and len(exited) < len(entered) and not recoveries)
    if unhealed:
        out.append(_incident(
            "headless_outage", 88, "headless outage (control plane down)",
            "the driver/KV went down and never came back within the "
            "deadline" + (" — the supervisor's restart budget is "
                          "exhausted" if exhausted else "") +
            (" — worker(s) aborted at the headless deadline"
             if aborts else ""),
            [e.get("id") for e in exhausted + aborts + entered + crashes],
            "workers trained on peer-to-peer only (no resize, no "
            "drain handling, no telemetry) until the deadline",
            "restart the launcher; raise "
            "HOROVOD_DRIVER_RESTART_LIMIT / inspect why every respawn "
            "died"))
    elif crashes:
        out.append(_incident(
            "driver_crash_recovered", 55, "driver crash (recovered)",
            f"the driver crashed {len(crashes)} time(s); each respawn "
            "replayed the WAL, re-claimed a higher epoch, and adopted "
            "the still-running workers",
            [e.get("id") for e in (crashes + recoveries)],
            "a control-plane observability gap of seconds; training "
            "never stopped (headless mode)",
            "none needed — verify adopted worker counts match; "
            "recurring crashes deserve a look at the driver host"))
    return out


def detect_shed_storm(ctx) -> List[dict]:
    sheds = [e for e in ctx["events"]
             if e.get("component") == "serve"
             and e.get("event") in _SHED_EVENTS]
    if len(sheds) < SHED_STORM_MIN:
        return []
    # densest window
    times = sorted(float(e.get("t_wall") or 0.0) for e in sheds)
    best, lo = 0, 0
    for hi in range(len(times)):
        while times[hi] - times[lo] > SHED_STORM_WINDOW_SEC:
            lo += 1
        best = max(best, hi - lo + 1)
    if best < SHED_STORM_MIN:
        return []
    reasons = [((e.get("detail") or {}).get("reason") or
                (e.get("detail") or {}).get("error") or "")
               for e in sheds]
    cache = sum(1 for r in reasons
                if "cache" in r or "capacity" in r or "block" in r)
    kind = "cache-exhaustion shed storm" if cache >= best // 2 \
        else "flash-crowd shed storm"
    return [_incident(
        "shed_storm", 70, kind,
        f"{len(sheds)} requests shed ({best} inside "
        f"{SHED_STORM_WINDOW_SEC:.0f}s)" +
        (" with cache/capacity exhaustion reasons — the paged KV "
         "cache ran out of blocks" if cache >= best // 2 else
         " — offered load exceeded fleet capacity"),
        [e.get("id") for e in sheds[:8]],
        f"{len(sheds)} client requests got 429/expired",
        "scale up (the autoscaler should have fired — check its "
        "cooldowns) or raise the cache block budget; verify priority "
        "classes shed in the right order",
        sheds=len(sheds), densest_window=best,
        cache_exhaustion=cache >= best // 2)]


def detect_flap(ctx) -> List[dict]:
    decides = [e for e in ctx["events"]
               if e.get("event") == "autoscale_decide"]
    actions = [(e.get("detail") or {}).get("action") for e in decides]
    flips = sum(1 for a, b in zip(actions, actions[1:])
                if a != b and a in ("up", "down") and b in ("up", "down"))
    if flips < 2:
        return []
    return [_incident(
        "flap", 60, "autoscale flapping",
        f"{len(decides)} autoscale decisions reversed direction "
        f"{flips} time(s) — hysteresis windows/cooldowns are too "
        "short for this load pattern",
        [e.get("id") for e in decides[:8]],
        "each flap is a resize: a full re-rendezvous paid for "
        "nothing",
        "raise HOROVOD_AUTOSCALE_UP_WINDOWS/DOWN_WINDOWS or the "
        "cooldowns so one noisy window can't resize the fleet",
        decisions=len(decides), direction_changes=flips)]


def detect_partition(ctx) -> List[dict]:
    stale = [e for e in ctx["events"]
             if e.get("event") == "discovery_stale"]
    healed = [e for e in ctx["events"]
              if e.get("event") == "discovery_recovered"]
    if not stale:
        return []
    if healed:
        return [_incident(
            "partition_healed", 50, "network partition (healed)",
            "serve discovery went unreachable and later recovered — a "
            "partition or control-plane restart separated the router "
            "from the KV, then healed",
            [e.get("id") for e in stale + healed],
            "routers served on their last-known worker table during "
            "the gap; no placements were lost to it",
            "none if brief; correlate with KV failover events above "
            "if any")]
    return [_incident(
        "partition", 72, "discovery partition (unhealed)",
        "serve discovery went stale and never recovered in this "
        "artifact window — routers are flying blind on a stale "
        "worker table",
        [e.get("id") for e in stale],
        "new workers are invisible to routers; dead ones keep "
        "receiving dispatch attempts until the retry path fails them",
        "check the KV endpoints the router holds; restart the router "
        "with fresh discovery if the KV moved")]


def detect_step_regression(ctx) -> List[dict]:
    """Straggler vs express-lane regression: one slow rank is a
    straggler (that machine); most ranks slowing together is a lane
    regression (the collective path got slower — express-lane demotion,
    fusion misconfig)."""
    stragglers = [e for e in ctx["events"]
                  if e.get("event") == "straggler"]
    anomalies = [e for e in ctx["events"]
                 if e.get("event") == "step_anomaly"]
    if not stragglers and not anomalies:
        return []
    ranks = {e.get("rank") for e in stragglers + anomalies
             if e.get("rank") is not None}
    fleet = 0
    for e in ctx["events"]:
        if e.get("event") == "resize":
            fleet = max(fleet, int((e.get("detail") or {})
                                   .get("slots") or 0))
    fl = ctx.get("flight_verdict") or {}
    if len(ranks) >= 2 and fleet and len(ranks) >= max(2, fleet // 2):
        return [_incident(
            "express_lane_regression", 65, "fleet-wide step regression",
            f"{len(ranks)} of {fleet} ranks flagged slow in the same "
            "window — not one sick machine but a shared-path "
            "regression (express-lane demotion, fusion/cycle "
            "misconfiguration, or network degradation)",
            [e.get("id") for e in (stragglers + anomalies)[:8]],
            "every step pays the regression until the knob is found",
            "diff the tuner's current bucket/fusion/express knobs "
            "against the last good run (hvd-top --tune); check "
            "hvd_tune_* gauges for a recent demotion",
            ranks=sorted(ranks), fleet=fleet)]
    lag = f" (flight analyzer: rank {fl['lagging_rank']} lagged " \
          f"{fl.get('lag_behind_us', 0) / 1e3:.0f}ms)" \
        if fl.get("lagging_rank") is not None else ""
    return [_incident(
        "straggler", 40, "straggler rank",
        f"rank(s) {sorted(ranks)} ran consistently slower than the "
        f"fleet median{lag} — one machine's problem (thermal, "
        "noisy neighbor, degraded link)",
        [e.get("id") for e in (stragglers + anomalies)[:8]],
        "synchronous steps run at the straggler's pace: the whole "
        "fleet pays its slowdown",
        "drain the slow host and let the elastic driver rebalance; "
        "check its thermals/neighbors before re-admitting",
        ranks=sorted(ranks))]


DETECTORS = (
    detect_dead_rank,
    detect_desync,
    detect_drain_race,
    detect_split_brain,
    detect_kv_leader_failover,
    detect_headless_outage,
    detect_shed_storm,
    detect_flap,
    detect_partition,
    detect_step_regression,
)


def diagnose(ctx) -> dict:
    """Run the detector pipeline; returns the ranked verdict."""
    incidents: List[dict] = []
    for det in DETECTORS:
        try:
            incidents += det(ctx)
        except Exception as e:  # noqa: BLE001 — one broken detector must
            incidents.append(_incident(  # not hide the others' findings
                "detector_error", 1, f"detector {det.__name__} failed",
                repr(e), [], "analysis gap", "fix the detector"))
    incidents.sort(key=lambda i: (-i["severity"], i["cause"]))
    return {
        "generated_at": time.time(),
        "analyzed": {
            "events": len(ctx["events"]),
            "journal_dir": ctx.get("journal_dir"),
            "kv_dirs": ctx.get("kv_dirs", []),
            "flight_dirs": ctx.get("flight_dirs", []),
            "divergences": len(ctx.get("divergences", [])),
        },
        "incident_count": len(incidents),
        "top_cause": incidents[0]["cause"] if incidents else None,
        "incidents": incidents,
    }


# ===========================================================================
# Output
# ===========================================================================

def render_verdict(verdict: dict) -> str:
    a = verdict["analyzed"]
    lines = [f"hvd-doctor verdict — {verdict['incident_count']} "
             f"incident(s) over {a['events']} fused event(s)"
             f" ({a['divergences']} conformance divergence(s))"]
    if not verdict["incidents"]:
        lines.append("  no incidents detected: the timeline is healthy")
    for n, inc in enumerate(verdict["incidents"], 1):
        lines.append(f"{n:3d}. [{inc['cause']}] {inc['title']} "
                     f"(severity {inc['severity']})")
        lines.append(f"     root cause : {inc['root_cause']}")
        if inc["evidence"]:
            lines.append(f"     evidence   : "
                         f"{', '.join(map(str, inc['evidence']))}")
        lines.append(f"     blast      : {inc['blast_radius']}")
        lines.append(f"     remediation: {inc['remediation']}")
    return "\n".join(lines)


def export_perfetto(ctx, out_path) -> dict:
    """The fused timeline as one Perfetto-loadable trace: flight dumps
    through the PR-5 lane machinery, journal/KV events as an instant
    lane per component."""
    from horovod_tpu.profiler import flight, trace_merge
    merged: List[dict] = []
    dumps = ctx.get("flight_dumps") or {}
    if dumps:
        merged += flight.to_perfetto(dumps)["traceEvents"]
    timeline = [e for e in ctx["events"] if e.get("source") != "flight"]
    walls = [float(e["t_wall"]) for e in timeline
             if e.get("t_wall") is not None]
    t0 = min(walls) if walls else 0.0
    instants = []
    for e in timeline:
        tw = e.get("t_wall")
        instants.append({
            "name": f"{e.get('component')}:{e.get('event')}",
            "ph": "X", "dur": 1,
            "ts": (float(tw) - t0) * 1e6 if tw is not None else 0.0,
            "tid": str(e.get("component")),
            "args": {"id": e.get("id"),
                     "control_epoch": e.get("control_epoch"),
                     "generation": e.get("generation"),
                     "detail": e.get("detail")},
        })
    merged += trace_merge._rewrite_engine_events(
        instants, engine_pid=trace_merge.DEFAULT_ENGINE_PID + 512,
        engine_label="hvd-doctor incident timeline", offset_us=0.0)
    return trace_merge.merge_traces([], jax_trace=merged,
                                    out_path=out_path)


def write_verdict_file(verdict: dict, journal_dir) -> Optional[str]:
    """Persist the verdict next to the journal (write-then-rename) so
    ``hvd-top`` can banner its age + incident count. Best-effort."""
    if not journal_dir:
        return None
    path = os.path.join(str(journal_dir), VERDICT_FILE)
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(verdict, f)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def read_verdict_file(journal_dir) -> Optional[dict]:
    """The newest persisted verdict, or None (hvd-top's banner read)."""
    try:
        with open(os.path.join(str(journal_dir), VERDICT_FILE)) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


# ===========================================================================
# CLI
# ===========================================================================

def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="hvd-doctor",
        description="offline incident analyzer: fuse event journals, KV "
                    "WALs, and flight dumps into one causally-ordered "
                    "timeline and name the root cause")
    p.add_argument("path", nargs="?",
                   help="artifact directory (journal/kv/flight "
                        "subdirectories are discovered; default "
                        "HOROVOD_JOURNAL_DIR, then "
                        "HOROVOD_SOAK_ARTIFACT_DIR)")
    p.add_argument("--journal-dir", help="explicit journal directory")
    p.add_argument("--kv-dir", help="explicit KV WAL directory")
    p.add_argument("--flight-dir", help="explicit flight-dump directory")
    p.add_argument("--perfetto", metavar="OUT",
                   help="export the fused timeline as a Perfetto trace")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable verdict")
    p.add_argument("--fail-on-incident", action="store_true",
                   help="exit 1 when any incident is detected (CI gates)")
    args = p.parse_args(argv)

    from horovod_tpu.common.env_registry import env_str
    path = args.path or args.journal_dir or \
        env_str("HOROVOD_JOURNAL_DIR") or \
        env_str("HOROVOD_SOAK_ARTIFACT_DIR")
    if not path:
        p.error("no artifact path: pass a directory or set "
                "HOROVOD_JOURNAL_DIR / HOROVOD_SOAK_ARTIFACT_DIR")
    ctx = build_timeline(path, journal_dir=args.journal_dir,
                         kv_dir=args.kv_dir, flight_dir=args.flight_dir)
    verdict = diagnose(ctx)
    written = write_verdict_file(
        verdict, ctx.get("journal_dir") or
        (args.journal_dir or env_str("HOROVOD_JOURNAL_DIR")))
    if args.perfetto:
        export_perfetto(ctx, args.perfetto)
    if args.as_json:
        print(json.dumps(verdict, indent=2, default=str))
    else:
        print(render_verdict(verdict))
        if written:
            print(f"(verdict persisted to {written})")
        if args.perfetto:
            print(f"(fused Perfetto timeline: {args.perfetto})")
    if args.fail_on_incident and verdict["incident_count"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
