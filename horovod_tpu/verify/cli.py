"""``hvd-check`` — explicit-state protocol model checker + conformance.

Usage::

    hvd-check                         # all specs, exhaustive at CI bound
    hvd-check --spec epoch --depth 40 # one spec, deeper bound
    hvd-check --mutant epoch_accept_stale_notify
                                      # seeded bug: expects a counterexample
    hvd-check --conformance DIR       # replay flight dumps + KV WALs
                                      #   + event journals
    hvd-check --list-specs / --list-mutants
    make check-protocols              # repo-root CI target
    make conformance                  # replay the latest soak artifacts

Exit status: 0 clean, 1 invariant violations / divergences found, 2
usage error. ``--mutant`` still exits 1 on a violation — the seeded-bug
tests assert the nonzero exit, so the CLI's contract stays one-valued:
"did the checker find something".
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from horovod_tpu.verify import conformance
from horovod_tpu.verify.checker import check
from horovod_tpu.verify.specs import MUTANTS, SPECS, make_spec

# The CI profile (`make check-protocols`, tests/test_verify.py): deep
# enough that every spec's reachable space closes (depths observed: 8-10),
# bounded so a runaway spec edit fails fast instead of eating the tier-1
# budget.
CI_DEPTH = 32
CI_MAX_STATES = 200_000


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="hvd-check",
        description="explicit-state model checker + runtime trace "
                    "conformance for the horovod_tpu control plane")
    p.add_argument("--spec", choices=sorted(SPECS),
                   help="check one spec (default: all)")
    p.add_argument("--mutant", choices=sorted(MUTANTS),
                   help="re-introduce a seeded historical bug and hunt "
                        "for its counterexample")
    p.add_argument("--depth", type=int, default=CI_DEPTH,
                   help=f"exploration depth bound (default {CI_DEPTH})")
    p.add_argument("--max-states", type=int, default=CI_MAX_STATES,
                   help="state-count safety cap")
    p.add_argument("--all-violations", action="store_true",
                   help="keep exploring after the first counterexample")
    p.add_argument("--conformance", metavar="DIR",
                   help="replay artifacts (flight_rank*.json dumps, KV "
                        "wal.log/snapshot.json, journal_*.log event "
                        "journals) under DIR against the protocol rules")
    p.add_argument("--kv-dir", help="explicit KV directory for "
                                    "--conformance")
    p.add_argument("--flight-dir", help="explicit flight-dump directory "
                                        "for --conformance")
    p.add_argument("--journal-dir", help="explicit event-journal "
                                         "directory for --conformance")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--list-specs", action="store_true")
    p.add_argument("--list-mutants", action="store_true")
    args = p.parse_args(argv)

    if args.list_specs:
        for name, cls in sorted(SPECS.items()):
            doc = (cls.__doc__ or "").strip().splitlines()[0]
            print(f"{name:8s} {doc}")
        return 0
    if args.list_mutants:
        for name, (spec, _kwarg, doc) in sorted(MUTANTS.items()):
            print(f"{name:30s} [{spec}] {doc}")
        return 0

    if args.conformance:
        report = conformance.check_artifacts(
            args.conformance, kv_dir=args.kv_dir,
            flight_dir=args.flight_dir, journal_dir=args.journal_dir)
        if args.as_json:
            print(json.dumps(report, indent=2))
        else:
            for line in report["checked"]:
                print(f"checked {line}")
            for line in report["divergences"]:
                print(f"DIVERGENCE: {line}")
            print(f"hvd-check conformance: {len(report['checked'])} "
                  f"artifact set(s), {len(report['divergences'])} "
                  "divergence(s)")
        return 1 if report["divergences"] else 0

    if args.mutant:
        specs = [make_spec(MUTANTS[args.mutant][0], mutant=args.mutant)]
    elif args.spec:
        specs = [make_spec(args.spec)]
    else:
        specs = [make_spec(name) for name in sorted(SPECS)]

    results = [check(s, depth=args.depth, max_states=args.max_states,
                     max_violations=0 if args.all_violations else 1)
               for s in specs]
    violations = [v for r in results for v in r.violations]

    if args.as_json:
        print(json.dumps({
            "results": [{
                "spec": r.spec, "states": r.states,
                "transitions": r.transitions, "depth": r.depth_reached,
                "exhaustive": not r.truncated,
                "violations": [{
                    "invariant": v.invariant, "doc": v.doc,
                    "trace": v.trace} for v in r.violations],
            } for r in results]}, indent=2))
    else:
        for r in results:
            print(r.summary())
        for v in violations:
            print()
            print(v.render())
        if args.mutant and violations:
            print(f"\nseeded bug `{args.mutant}` reproduced: "
                  f"{MUTANTS[args.mutant][2]}")
        elif args.mutant:
            print(f"\nWARNING: seeded bug `{args.mutant}` produced NO "
                  "counterexample — the invariant guarding it has lost "
                  "its teeth", file=sys.stderr)
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
