"""Shared protocol rules the specs and the conformance checker both use.

Each rule here is a pure function mirroring one decision point in the
real code, with a test asserting agreement against the real
implementation (``tests/test_verify.py``) — the spec-vs-code contract
the ISSUE calls "imported from or asserted against the real code".
"""

from __future__ import annotations

from typing import Optional, Tuple

# outcomes of admit_epoch
FENCED = "fenced"   # strictly-older claim: 409 / StaleEpochError
ADOPT = "adopt"     # newer claim: server adopts + persists it
OK = "ok"           # equal claim or epoch-less write


def admit_epoch(current: int, claimed: Optional[int]) \
        -> Tuple[str, int]:
    """The KV server's epoch-fencing rule (``KVServer._check_epoch_locked``
    in ``runner/http_kv.py``): ``(outcome, new_server_epoch)``.

    - epoch-less writes (claimed is None) pass untouched;
    - strictly-older claims are fenced;
    - newer claims advance (and persist) the server epoch."""
    if claimed is None:
        return OK, current
    if claimed < current:
        return FENCED, current
    if claimed > current:
        return ADOPT, claimed
    return OK, current


def worker_accepts(floor: int, offered: Optional[int]) \
        -> Tuple[bool, int]:
    """The worker-side fencing floor (``runner/elastic/worker.py
    observe_epoch``): ``(accepted, new_floor)``. ``None`` = unfenced
    legacy record, accepted; at/above the floor accepted and raises it;
    strictly below rejected."""
    if offered is None:
        return True, floor
    if offered < floor:
        return False, floor
    return True, offered


def majority(replicas: int) -> int:
    """Quorum size for a replica set (``runner/replica_kv.py``): a write
    is committed — and an election won — only when this many replicas
    (leader/candidate included) hold it."""
    return replicas // 2 + 1


def vote_grants(voter_epoch: int, voter_last_term: int, voter_len: int,
                cand_epoch: int, cand_last_term: int, cand_len: int,
                heard_from_leader: bool) -> bool:
    """The replica election grant rule (``ReplicaKVServer`` vote
    handler): a voter grants a candidate iff

    - it has NOT heard from a live leader inside the lease window (the
      clock assumption that makes at-most-one-leaseholder hold), and
    - the candidate proposes a strictly newer epoch, and
    - the candidate's WAL is at least as up-to-date as the voter's by
      the Raft ordering: ``(last-record term, length)`` compared
      lexicographically. Bare length is NOT enough — two equal-length
      logs can diverge (a deposed leader's un-acked suffix vs the
      successor's committed suffix), and only the term of the last
      record tells them apart. A majority-acked write is on some voter
      in every quorum, and that voter's (term, length) dominates any
      candidate missing it, so no acked write can be missing from the
      new leader."""
    return (not heard_from_leader) and cand_epoch > voter_epoch \
        and (cand_last_term, cand_len) >= (voter_last_term, voter_len)


def express_eligible(size_bytes: int, threshold: int,
                     grouped: bool = False,
                     data_bearing: bool = True) -> bool:
    """The express-lane partition rule (``Controller::LowLatencyEligible``
    in ``engine/src/controller.cc``): small, ungrouped, data-bearing
    responses peel onto the low-latency lane. Every rank must compute
    this identically or cross-rank exec order desyncs — the invariant
    the cycle spec checks."""
    return data_bearing and not grouped and size_bytes <= threshold
