"""Executable protocol specs for the control plane.

Explicit state machines covering the interlocking control protocols
(the ``SPECS`` registry at the bottom is the authoritative list —
``hvd-check`` and ``make check-protocols`` enumerate it):

- :class:`CycleSpec` — the coordination cycle + fast abort + the
  express-lane response partition (cross-rank exec-order agreement);
- :class:`EpochSpec` — control-epoch fencing: KV 409/adopt rules, the
  worker floor, driver recovery with heartbeat adoption;
- :class:`DrainSpec` — preemption drain → shard handoff → resize, with
  the driver's scan-before-refresh heartbeat ordering and the reap-time
  last-chance drain check;
- :class:`TuneSpec` — the cycle-boundary ``TunedParams`` broadcast;
- :class:`AutoscaleSpec` — the SLO→fleet-size decision loop;
- :class:`PagedCacheSpec` — serving block-paged KV-cache accounting;
- :class:`ScrapeSpec` — the tiered telemetry scrape plane;
- :class:`ReplicaSpec` — leader-lease KV replication: majority-ack
  commit, epoch-as-term elections, self-fencing, divergence repair;
- :class:`JournalSpec` — the durable event journal: flush-then-ack,
  segment rotation, closed-segment retention, crash-loss accounting.

Spec constants come from the real code: the express threshold and flag
bits are parsed out of ``engine/src`` (``engine_constants``), KV keys in
trace labels come from ``common/kv_keys.py``, and the epoch rules are
the shared functions in ``verify/rules.py`` that tests assert against
the real ``KVServer``/``observe_epoch``.

Seeded historical bugs are re-introducible as **mutations** (the
``MUTANTS`` registry): ``hvd-check --mutant <name>`` must produce a
counterexample for each, which is what proves the invariants have teeth.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Tuple

from horovod_tpu.common import kv_keys
from horovod_tpu.verify import engine_constants, rules
from horovod_tpu.verify.spec import Invariant, Spec


def _rep(t: tuple, i: int, v) -> tuple:
    return t[:i] + (v,) + t[i + 1:]


# ===========================================================================
# Coordination cycle + fast abort + express-lane partition
# ===========================================================================

class CycleState(NamedTuple):
    pending: tuple        # per rank: frozenset of negotiating tensor names
    next_idx: tuple       # per rank: position in the submit script
    exec_log: tuple       # per rank: tuple of executed tensor names
    abort_req: tuple      # per rank: requested a fast abort
    aborted: tuple        # per rank: session dead
    crashed: tuple        # per rank: process dead (fault)
    missed_abort: bool    # a cycle completed without honoring a signal
    crashes_left: int
    aborts_left: int


class CycleSpec(Spec):
    """Two engine ranks submit the same tensor program (a sub-threshold
    gradient and a bulk gradient) at independent speeds; the cycle
    negotiates the common set, peels the express lane, and executes.
    Faults: one rank crash, one explicit abort request."""

    # (name, size_bytes): one tensor under the express threshold, one
    # over. Named so the express-first order differs from plain sorted
    # order — a divergent partition must actually reorder execution.
    SUBMITS = (("tiny_update", 1024), ("dense_grad", 1 << 20))

    def __init__(self, ranks: int = 2, rank_divergent_express: bool = False,
                 ignore_abort: bool = False, crashes: int = 1,
                 aborts: int = 1):
        super().__init__(name="cycle", mutations=tuple(
            m for m, on in [("rank_divergent_express",
                             rank_divergent_express),
                            ("ignore_abort", ignore_abort)] if on))
        self.ranks = ranks
        self.rank_divergent_express = rank_divergent_express
        self.ignore_abort = ignore_abort
        self.crashes = crashes
        self.aborts = aborts
        self.threshold = engine_constants.low_latency_threshold_default()
        # the abort flag bit must exist in the real coordination word —
        # the fast-abort protocol this spec models rides it
        self.abort_bit = engine_constants.flag_bits()["kFlagAbort"]

    def initial(self) -> CycleState:
        n = self.ranks
        return CycleState(
            pending=(frozenset(),) * n, next_idx=(0,) * n,
            exec_log=((),) * n, abort_req=(False,) * n,
            aborted=(False,) * n, crashed=(False,) * n,
            missed_abort=False, crashes_left=self.crashes,
            aborts_left=self.aborts)

    def _alive(self, s: CycleState) -> List[int]:
        return [r for r in range(self.ranks)
                if not s.crashed[r] and not s.aborted[r]]

    def _partition(self, rank: int, common: frozenset) \
            -> Tuple[tuple, tuple]:
        """(express, bulk) exec order for one rank — identical on every
        rank in the real controller; the mutation gives rank >= 1 a
        divergent threshold (the historical hazard class: rank-dependent
        fusion/express eligibility)."""
        threshold = self.threshold
        if self.rank_divergent_express and rank >= 1:
            threshold = 0
        sizes = dict(self.SUBMITS)
        express = tuple(sorted(
            t for t in common
            if rules.express_eligible(sizes[t], threshold)))
        bulk = tuple(sorted(t for t in common if t not in express))
        return express, bulk

    def actions(self, s: CycleState):
        out = []
        alive = self._alive(s)
        for r in alive:
            if s.next_idx[r] < len(self.SUBMITS):
                name = self.SUBMITS[s.next_idx[r]][0]
                out.append((
                    f"rank{r}.enqueue({name})",
                    s._replace(
                        pending=_rep(s.pending, r,
                                     s.pending[r] | {name}),
                        next_idx=_rep(s.next_idx, r, s.next_idx[r] + 1))))
        for r in alive:
            if s.crashes_left > 0:
                out.append((f"fault: rank{r} crashes",
                            s._replace(crashed=_rep(s.crashed, r, True),
                                       crashes_left=s.crashes_left - 1)))
            if s.aborts_left > 0 and not s.abort_req[r]:
                out.append((
                    f"rank{r}.hvdtpu_abort()",
                    s._replace(abort_req=_rep(s.abort_req, r, True),
                               aborts_left=s.aborts_left - 1)))
        if alive:
            out.append(self._cycle(s, alive))
        return out

    def _cycle(self, s: CycleState, alive: List[int]):
        abort_signal = any(s.crashed) or any(s.abort_req[r] for r in alive)
        if abort_signal and not self.ignore_abort:
            # fast abort: the kFlagAbort bit rides the OR'd coordination
            # word, so EVERY surviving rank fails this same cycle
            aborted = s.aborted
            for r in alive:
                aborted = _rep(aborted, r, True)
            return (f"cycle: flags|=kFlagAbort(bit {self.abort_bit}) -> "
                    f"all alive ranks abort",
                    s._replace(aborted=aborted))
        if abort_signal:
            # MUTATION ignore_abort: the cycle proceeds as if the flag
            # word carried nothing — the missed signal is the violation
            s = s._replace(missed_abort=True)
        common = frozenset.intersection(
            *[s.pending[r] for r in range(self.ranks)]) \
            if self.ranks else frozenset()
        pending = s.pending
        exec_log = s.exec_log
        for r in range(self.ranks):
            pending = _rep(pending, r, s.pending[r] - common)
            express, bulk = self._partition(r, common)
            exec_log = _rep(exec_log, r, s.exec_log[r] + express + bulk)
        label = "cycle: negotiate " + (
            f"{sorted(common)}" if common else "(nothing common)")
        return label, s._replace(pending=pending, exec_log=exec_log)

    @property
    def invariants(self) -> List[Invariant]:
        def exec_agreement(s: CycleState) -> bool:
            logs = list(s.exec_log)
            for i in range(len(logs)):
                for j in range(i + 1, len(logs)):
                    a, b = logs[i], logs[j]
                    n = min(len(a), len(b))
                    if a[:n] != b[:n]:
                        return False
            return True

        def abort_honored(s: CycleState) -> bool:
            return not s.missed_abort

        return [
            Invariant(
                "exec_order_agreement",
                "every pair of ranks executes negotiated collectives in "
                "the same order (express-lane partition included) — "
                "divergence deadlocks the data plane", exec_agreement),
            Invariant(
                "abort_within_one_cycle",
                "a pending crash/abort signal is honored by the very "
                "next coordination cycle on every surviving rank",
                abort_honored),
        ]


# ===========================================================================
# Control-epoch fencing (split-brain protection, adoption)
# ===========================================================================

class DriverS(NamedTuple):
    alive: bool
    fenced: bool        # observed a 409; stood down
    epoch: int
    gen: int            # last generation this driver published
    last_notify: Optional[tuple]  # (gen, epoch) at this driver's replica
    recovered: bool     # ran its adoption pass (recovered drivers only)
    writes_left: int


class EpochState(NamedTuple):
    kv_epoch: int         # authoritative (durable) server epoch
    persist_epoch: int    # what the epoch file holds
    last_write_epoch: int  # epoch of the last ACCEPTED mutation
    write_regressed: bool  # an older-epoch write landed after a newer one
    kv_notify: Optional[tuple]  # (gen, epoch) in the durable store
    drivers: tuple        # DriverS per driver slot (0 = original, 1 = respawn)
    worker_alive: bool
    worker_procs: int     # live processes for the one modeled slot
    worker_floor: int
    worker_gen: int
    worker_max_gen: int   # highest generation ever accepted
    respawns_left: int
    partitions_left: int
    kills_left: int
    partitioned: tuple    # per driver: supervisor presumes it dead


class EpochSpec(Spec):
    """One durable KV, one worker slot, an original driver and one
    supervisor respawn. Faults: a driver partition (presumed dead but
    still writing), a worker kill. The lingering driver's own KV replica
    is modeled per-driver (``last_notify``) — the window the worker-side
    epoch floor exists for."""

    def __init__(self, accept_stale_notify: bool = False,
                 no_fence: bool = False, no_adoption_check: bool = False):
        super().__init__(name="epoch", mutations=tuple(
            m for m, on in [("accept_stale_notify", accept_stale_notify),
                            ("no_fence", no_fence),
                            ("no_adoption_check", no_adoption_check)]
            if on))
        self.accept_stale_notify = accept_stale_notify
        self.no_fence = no_fence
        self.no_adoption_check = no_adoption_check

    def initial(self) -> EpochState:
        d0 = DriverS(alive=True, fenced=False, epoch=1, gen=0,
                     last_notify=None, recovered=False, writes_left=2)
        d1 = DriverS(alive=False, fenced=False, epoch=0, gen=0,
                     last_notify=None, recovered=False, writes_left=2)
        return EpochState(
            kv_epoch=1, persist_epoch=1, last_write_epoch=1,
            write_regressed=False, kv_notify=None, drivers=(d0, d1),
            worker_alive=True, worker_procs=1, worker_floor=1,
            worker_gen=0, worker_max_gen=0,
            respawns_left=1, partitions_left=1, kills_left=1,
            partitioned=(False, False))

    def actions(self, s: EpochState):
        out = []
        # fault: partition the original driver (supervisor thinks it
        # crashed; the process lingers and keeps trying to act)
        for i, d in enumerate(s.drivers):
            if d.alive and not s.partitioned[i] and s.partitions_left > 0:
                out.append((
                    f"fault: driver{i} partitioned (supervisor presumes "
                    f"it dead; process lingers)",
                    s._replace(partitioned=_rep(s.partitioned, i, True),
                               partitions_left=s.partitions_left - 1)))
        # supervisor respawn: a fresh driver over the same KV dir; the
        # durable replay bumps the persistent epoch (KVServer contract)
        if s.respawns_left > 0 and not s.drivers[1].alive and \
                any(s.partitioned[i] for i in range(2)):
            new_epoch = s.persist_epoch + 1
            rec_gen = s.kv_notify[0] if s.kv_notify else 0
            d1 = DriverS(alive=True, fenced=False, epoch=new_epoch,
                         gen=rec_gen, last_notify=None, recovered=False,
                         writes_left=2)
            out.append((
                f"supervisor respawns driver1 (control epoch "
                f"{s.persist_epoch} -> {new_epoch})",
                s._replace(drivers=_rep(s.drivers, 1, d1),
                           kv_epoch=new_epoch, persist_epoch=new_epoch,
                           respawns_left=s.respawns_left - 1)))
        # recovered driver's adoption pass: adopt a live (heartbeating)
        # worker, spawn only for a dead slot
        d1 = s.drivers[1]
        if d1.alive and not d1.recovered:
            if s.worker_alive and not self.no_adoption_check:
                out.append((
                    f"driver1 adopts live worker from "
                    f"{kv_keys.worker_heartbeat('host', 0)}",
                    s._replace(drivers=_rep(
                        s.drivers, 1, d1._replace(recovered=True)))))
            else:
                label = ("driver1 respawns the slot (MUTATION: skipped "
                         "the heartbeat adoption check)"
                         if s.worker_alive else
                         "driver1 spawns the dead slot")
                out.append((
                    label,
                    s._replace(
                        drivers=_rep(s.drivers, 1,
                                     d1._replace(recovered=True)),
                        worker_alive=True,
                        worker_procs=s.worker_procs + 1,
                        worker_floor=max(s.worker_floor, d1.epoch)
                        if not s.worker_alive else s.worker_floor)))
        # driver writes notify (the resize push) claiming its epoch
        for i, d in enumerate(s.drivers):
            if d.alive and not d.fenced and d.writes_left > 0:
                out.append(self._write_notify(s, i))
        # worker observes a notify — from the durable KV or from a
        # lingering driver's replica
        if s.worker_alive:
            if s.kv_notify is not None:
                act = self._observe(s, s.kv_notify, "durable KV")
                if act is not None:
                    out.append(act)
            for i, d in enumerate(s.drivers):
                if d.last_notify is not None:
                    act = self._observe(
                        s, d.last_notify, f"driver{i}'s lingering replica")
                    if act is not None:
                        out.append(act)
        # fault: kill the worker (heartbeats stop)
        if s.worker_alive and s.kills_left > 0:
            out.append((
                "fault: worker killed (heartbeats stop)",
                s._replace(worker_alive=False,
                           worker_procs=max(0, s.worker_procs - 1),
                           kills_left=s.kills_left - 1)))
        return out

    def _write_notify(self, s: EpochState, i: int):
        d = s.drivers[i]
        gen = d.gen + 1
        rec = (gen, d.epoch)
        outcome, new_epoch = rules.admit_epoch(s.kv_epoch, d.epoch)
        if outcome == rules.FENCED and not self.no_fence:
            # the 409: the stale driver stands down; its replica still
            # holds whatever it last served
            return (
                f"kv 409s driver{i}'s `{kv_keys.notify()}` write "
                f"(offered {d.epoch} < current {s.kv_epoch}); "
                f"driver{i} stands down",
                s._replace(drivers=_rep(
                    s.drivers, i,
                    d._replace(fenced=True,
                               writes_left=d.writes_left - 1))))
        regressed = s.write_regressed or d.epoch < s.last_write_epoch
        return (
            f"driver{i} writes `{kv_keys.notify()}` = (gen {gen}, "
            f"epoch {d.epoch})",
            s._replace(
                kv_epoch=new_epoch,
                persist_epoch=max(s.persist_epoch, new_epoch),
                last_write_epoch=max(s.last_write_epoch, d.epoch),
                write_regressed=regressed,
                kv_notify=rec,
                drivers=_rep(s.drivers, i, d._replace(
                    gen=gen, last_notify=rec,
                    writes_left=d.writes_left - 1))))

    def _observe(self, s: EpochState, rec: tuple, source: str):
        gen, epoch = rec
        accepted, new_floor = rules.worker_accepts(s.worker_floor, epoch)
        if not accepted and not self.accept_stale_notify:
            return None  # rejection is a no-op, not a transition
        if not accepted:
            label = (f"worker accepts STALE notify gen {gen} epoch "
                     f"{epoch} from {source} (MUTATION: floor check "
                     f"skipped)")
            new_floor = s.worker_floor
        else:
            if gen == s.worker_gen:
                return None
            label = (f"worker observes notify gen {gen} (epoch {epoch}, "
                     f"{source}); resets into it")
        return (label, s._replace(
            worker_floor=new_floor, worker_gen=gen,
            worker_max_gen=max(s.worker_max_gen, gen)))

    @property
    def invariants(self) -> List[Invariant]:
        return [
            Invariant(
                "no_split_brain",
                "once a newer-epoch driver has mutated the store, a "
                "strictly-older epoch's mutation can never land (two "
                "live drivers acting at the same time)",
                lambda s: not s.write_regressed),
            Invariant(
                "epoch_monotone_persisted",
                "the server epoch equals the persisted epoch file — an "
                "adopted newer claim is durable before it fences anyone",
                lambda s: s.kv_epoch == s.persist_epoch),
            Invariant(
                "worker_generation_monotonic",
                "a worker never resets backward into an older generation "
                "(a fenced-out driver's stale notify must not roll a "
                "worker back)",
                lambda s: s.worker_gen == s.worker_max_gen),
            Invariant(
                "no_double_spawn",
                "the one modeled slot never has two live processes "
                "(recovery must adopt heartbeating workers, not respawn "
                "them)",
                lambda s: s.worker_procs <= 1),
        ]


# ===========================================================================
# Preemption drain -> shard handoff -> resize
# ===========================================================================

class DrainState(NamedTuple):
    wphase: str            # running|announced|handed_off|exited|killed|reaped
    committed: bool        # a commit boundary passed (shard acknowledged)
    buddy: bool            # ring-buddy replica of the committed shard
    kv_drain: bool         # drain/<host>/<slot> landed
    kv_handoff: bool       # shard_handoff/w<N>/<r> landed
    kv_drained_record: bool  # DRAINED registry record written at exit
    drv_knows: bool        # driver registered the drain (host held out)
    pc: int                # heartbeat program counter (index into steps)
    drain_visible_at_hb: bool  # kv_drain at the current heartbeat's start
    placed_on_doomed: bool
    false_completion: bool
    was_killed: bool
    kills_left: int


class DrainSpec(Spec):
    """One draining worker, one driver heartbeat loop. The heartbeat is
    three atomic steps whose order IS the protocol: drain scan, then
    discovery refresh + rebalance, then reap. The PR-9 historical race
    is re-introduced by swapping the first two (``scan_after_refresh``);
    the reap-time last-chance drain check is removable with
    ``no_last_chance``; ``no_buddy`` drops commit-time replication."""

    def __init__(self, scan_after_refresh: bool = False,
                 no_last_chance: bool = False, no_buddy: bool = False):
        super().__init__(name="drain", mutations=tuple(
            m for m, on in [("scan_after_refresh", scan_after_refresh),
                            ("no_last_chance", no_last_chance),
                            ("no_buddy", no_buddy)] if on))
        self.no_last_chance = no_last_chance
        self.no_buddy = no_buddy
        self.steps = ["refresh", "scan"] if scan_after_refresh \
            else ["scan", "refresh"]
        self.steps.append("reap")

    def initial(self) -> DrainState:
        return DrainState(
            wphase="running", committed=False, buddy=False,
            kv_drain=False, kv_handoff=False, kv_drained_record=False,
            drv_knows=False, pc=0, drain_visible_at_hb=False,
            placed_on_doomed=False, false_completion=False,
            was_killed=False, kills_left=1)

    def actions(self, s: DrainState):
        out = []
        # -- worker side ----------------------------------------------------
        if s.wphase in ("running", "announced") and not s.committed:
            out.append((
                "worker commits a step (shard acknowledged; ring-buddy "
                "replica lands)" if not self.no_buddy else
                "worker commits a step (MUTATION: buddy replication "
                "skipped)",
                s._replace(committed=True, buddy=not self.no_buddy)))
        if s.wphase == "running":
            out.append((
                "SIGTERM: preemption notice (drain requested; KV "
                "announce goes async)",
                s._replace(wphase="announced")))
        if s.wphase in ("announced", "handed_off") and not s.kv_drain:
            # the announcement is asynchronous (a thread leaves the
            # signal context) — interleavings where it lands late, or
            # never lands before the exit, are explored for free because
            # landing is just another action the scheduler may not pick
            out.append((
                f"async `{kv_keys.drain('host', 0)}` announcement lands",
                s._replace(kv_drain=True)))
        if s.wphase == "announced" and s.committed:
            out.append((
                f"worker publishes `{kv_keys.shard_handoff(2, 1)}` at "
                "the commit boundary",
                s._replace(wphase="handed_off", kv_handoff=True)))
        if s.wphase == "handed_off":
            out.append((
                "worker records DRAINED and exits 0",
                s._replace(wphase="exited", kv_drained_record=True)))
        if s.wphase in ("running", "announced", "handed_off") and \
                s.kills_left > 0:
            out.append((
                "fault: host dies (worker killed mid-drain)",
                s._replace(wphase="killed", was_killed=True,
                           kills_left=s.kills_left - 1)))
        # -- driver heartbeat -----------------------------------------------
        step = self.steps[s.pc]
        out.append(self._hb_step(s, step))
        return out

    def _hb_step(self, s: DrainState, step: str):
        nxt = (s.pc + 1) % len(self.steps)
        ns = s._replace(pc=nxt)
        if s.pc == 0:
            # heartbeat begins: record what was already visible
            ns = ns._replace(drain_visible_at_hb=s.kv_drain)
        if step == "scan":
            if s.kv_drain:
                return ("driver heartbeat: drain scan sees "
                        f"`{kv_keys.drain('host', 0)}`; host held out",
                        ns._replace(drv_knows=True))
            return "driver heartbeat: drain scan (nothing announced)", ns
        if step == "refresh":
            includes = not s.drv_knows
            doomed = s.placed_on_doomed or (
                includes and ns.drain_visible_at_hb)
            label = ("driver heartbeat: refresh + rebalance "
                     + ("EXCLUDES the draining host"
                        if not includes else "places onto the host"))
            return label, ns._replace(placed_on_doomed=doomed)
        # reap
        if s.wphase == "exited":
            if s.drv_knows:
                return ("driver reap: known drain exited (clean "
                        "departure)", ns._replace(wphase="reaped"))
            last_chance = (s.kv_drain or s.kv_drained_record) and \
                not self.no_last_chance
            if last_chance:
                return ("driver reap: exit 0 + last-chance drain check "
                        "hits (KV key / DRAINED record) -> treated as "
                        "drain", ns._replace(wphase="reaped",
                                             drv_knows=True))
            return ("driver reap: exit 0 misread as JOB COMPLETION",
                    ns._replace(wphase="reaped", false_completion=True))
        if s.wphase == "killed":
            return ("driver reap: kill detected -> failure path "
                    "(blacklist/rebalance)", ns._replace(wphase="reaped"))
        return "driver heartbeat: reap (nothing exited)", ns

    @property
    def invariants(self) -> List[Invariant]:
        def no_shard_loss(s: DrainState) -> bool:
            if not s.committed or not s.was_killed:
                return True
            return s.kv_handoff or s.buddy

        return [
            Invariant(
                "no_false_completion",
                "a drained worker's exit 0 is never misread as job "
                "completion (the PR-9 same-heartbeat race)",
                lambda s: not s.false_completion),
            Invariant(
                "no_placement_on_announced_host",
                "a rebalance never places onto a host whose drain "
                "announcement was visible before the heartbeat began "
                "(drain scan runs before discovery refresh)",
                lambda s: not s.placed_on_doomed),
            Invariant(
                "no_acknowledged_shard_loss",
                "once a commit acknowledged the shard, a kill leaves a "
                "recovery source (KV handoff or ring-buddy replica)",
                no_shard_loss),
        ]


# ===========================================================================
# Cycle-boundary TunedParams broadcast
# ===========================================================================

class TuneState(NamedTuple):
    staged: int     # version staged on the coordinator
    applied: tuple  # per rank: applied version
    routing: tuple  # per rank: applied data-plane routing version
    pushes_left: int
    env_reads_left: int  # budget for the env-divergence mutation


# Sentinel routing value an env read installs (distinct from any staged
# broadcast version, like a rank-local HOROVOD_RING_THRESHOLD_BYTES).
_ENV_ROUTING = -7


class TuneSpec(Spec):
    """The frontend tuner pushes knob records (``hvdtpu_set_tuned_params``)
    that must be adopted by EVERY rank at the same coordination-cycle
    boundary — rank-divergent fusion knobs desync exec order, and
    rank-divergent data-plane ROUTING knobs (ring threshold / hierarchy /
    small-tensor algo, carried by the same record since ABI 10) would put
    two ranks on different collective algorithms and deadlock the
    transports. The ``apply_inline`` mutation re-introduces the hazard
    the staged broadcast exists to prevent: applying the push immediately
    on the coordinator. The ``env_divergent_routing`` mutation
    re-introduces the pre-ABI-10 behavior this PR removed: a rank reading
    ``HOROVOD_RING_THRESHOLD_BYTES`` straight off its own environment
    instead of adopting the broadcast."""

    def __init__(self, ranks: int = 2, apply_inline: bool = False,
                 env_divergent_routing: bool = False):
        super().__init__(name="tune", mutations=tuple(
            m for m, on in [("apply_inline", apply_inline),
                            ("env_divergent_routing",
                             env_divergent_routing)] if on))
        self.ranks = ranks
        self.apply_inline = apply_inline
        self.env_divergent_routing = env_divergent_routing

    def initial(self) -> TuneState:
        return TuneState(staged=0, applied=(0,) * self.ranks,
                         routing=(0,) * self.ranks, pushes_left=2,
                         env_reads_left=1 if self.env_divergent_routing
                         else 0)

    def actions(self, s: TuneState):
        # A lost/aborted param broadcast needs no explicit fault action:
        # "the cycle didn't apply" is just the scheduler never picking
        # the cycle transition, which the interleaving exploration
        # already covers (a real broadcast failure fast-aborts the whole
        # cycle — CycleSpec's territory).
        out = []
        if s.pushes_left > 0:
            v = s.staged + 1
            applied = s.applied
            label = f"tuner pushes TunedParams v{v} (staged)"
            if self.apply_inline:
                applied = _rep(applied, 0, v)
                label = (f"tuner pushes TunedParams v{v} (MUTATION: "
                         "applied inline on the coordinator)")
            out.append((label, s._replace(
                staged=v, applied=applied,
                pushes_left=s.pushes_left - 1)))
        if s.env_reads_left > 0:
            for r in range(self.ranks):
                out.append((
                    f"rank {r} seeds its routing from its own env "
                    "(MUTATION: HOROVOD_RING_THRESHOLD_BYTES read "
                    "outside the broadcast)",
                    s._replace(routing=_rep(s.routing, r, _ENV_ROUTING),
                               env_reads_left=s.env_reads_left - 1)))
        out.append((
            f"cycle boundary: SynchronizeParameters broadcast applies "
            f"v{s.staged} (params + routing) on every rank",
            s._replace(applied=(s.staged,) * self.ranks,
                       routing=(s.staged,) * self.ranks)))
        return out

    @property
    def invariants(self) -> List[Invariant]:
        return [
            Invariant(
                "params_agree_between_cycles",
                "between coordination cycles every rank runs the same "
                "applied TunedParams (rank-divergent fusion/express "
                "knobs desync exec order)",
                lambda s: len(set(s.applied)) == 1),
            Invariant(
                "routing_agrees_between_cycles",
                "between coordination cycles every rank runs the same "
                "data-plane routing knobs (a split ring-threshold / "
                "hierarchy / small-tensor decision deadlocks the "
                "transports mid-collective)",
                lambda s: len(set(s.routing)) == 1),
            Invariant(
                "applied_never_ahead_of_staged",
                "no rank applies a params version the coordinator has "
                "not staged",
                lambda s: all(v <= s.staged for v in s.applied)),
        ]


# ===========================================================================
# Traffic-driven autoscaler: decide -> drain -> resize -> ack
# ===========================================================================

class AutoState(NamedTuple):
    fleet: int            # accepting serving workers
    spot: str             # spot-preemption drain: none|draining|done
    auto: str             # autoscale (scale-down) drain: none|draining|done
    pressure: bool        # offered load above the SLO bound
    hot: int              # consecutive breached windows (capped)
    idle: int             # consecutive idle windows (capped)
    since: int            # windows since the last acted decision (capped)
    last_dir: int         # +1 up / -1 down / 0 none yet
    rec: Optional[tuple]  # (action, state, epoch, victim_draining) in KV
    epoch: int            # acting driver's control epoch
    kv_epoch: int         # authoritative durable epoch
    crashed: bool         # driver dead, supervisor respawn pending
    old_alive: bool       # pre-crash driver lingers with work left
    old_rec: Optional[tuple]  # the lingering driver's replica of its record
    crashes_left: int
    kills_left: int
    spikes_left: int
    recedes_left: int
    preempts_left: int
    flap: bool            # opposite decisions within one hysteresis window
    lost_acked: bool      # a second preemption notice force-killed a drain
    stale_applied: bool   # a fenced-out driver's decision mutated the fleet
    unclamped: bool       # a resize left [MIN, MAX]


class AutoscaleSpec(Spec):
    """One autoscaled serving fleet, one driver (+ a supervisor respawn),
    binary offered load. The policy needs HYST consecutive breached/idle
    windows before deciding (hysteresis, the real default from
    ``env_registry``); every decision is a durable KV record advancing
    ``decide -> drain -> resize -> ack`` that a recovered driver RESUMES.
    Faults: a flash crowd arriving/receding, a spot-preemption drain, a
    worker SIGKILL, a driver crash + respawn with a lingering stale-epoch
    predecessor. Mutations re-introduce the three seeded hazards:
    ``no_hysteresis`` (single-window decisions flap), ``victim_draining``
    (scale-down picks the already-draining worker — the repeated
    preemption notice force-exits it, preempt.py:86-92, dropping its
    acked requests), ``no_epoch_fence`` (the fenced-out pre-crash
    driver's decision write lands after recovery)."""

    MIN, MAX = 1, 2

    def __init__(self, no_hysteresis: bool = False,
                 victim_draining: bool = False,
                 no_epoch_fence: bool = False):
        super().__init__(name="autoscale", mutations=tuple(
            m for m, on in [("no_hysteresis", no_hysteresis),
                            ("victim_draining", victim_draining),
                            ("no_epoch_fence", no_epoch_fence)] if on))
        self.no_hysteresis = no_hysteresis
        self.victim_draining = victim_draining
        self.no_epoch_fence = no_epoch_fence
        # the real hysteresis default — the spec checks the shipped
        # configuration, not an invented one
        from horovod_tpu.common.env_registry import REGISTRY
        self.hyst = 1 if no_hysteresis \
            else int(REGISTRY["HOROVOD_AUTOSCALE_UP_WINDOWS"].default)
        self.window = int(REGISTRY["HOROVOD_AUTOSCALE_UP_WINDOWS"].default)

    def initial(self) -> AutoState:
        return AutoState(
            fleet=2, spot="none", auto="none", pressure=False,
            hot=0, idle=0, since=self.window, last_dir=0, rec=None,
            epoch=1, kv_epoch=1, crashed=False, old_alive=False,
            old_rec=None, crashes_left=1, kills_left=1, spikes_left=1,
            recedes_left=1, preempts_left=1, flap=False, lost_acked=False,
            stale_applied=False, unclamped=False)

    # -- decision machinery ---------------------------------------------------

    def _tick(self, s: AutoState):
        hot = min(s.hot + 1, self.hyst) if s.pressure else 0
        idle = min(s.idle + 1, self.hyst) if not s.pressure else 0
        since = min(s.since + 1, self.window)
        ns = s._replace(hot=hot, idle=idle, since=since)
        in_flight = s.rec is not None and s.rec[1] != "ack"
        if not in_flight and hot >= self.hyst and s.fleet < self.MAX:
            flap = s.flap or (s.last_dir == -1 and since < self.window)
            return (f"autoscaler tick: {self.hyst} breached window(s) -> "
                    f"decide scale-UP (`{kv_keys.autoscale_decision()}` "
                    f"state=decide, epoch {s.epoch})",
                    ns._replace(rec=("up", "decide", s.epoch, False),
                                hot=0, idle=0, since=0, last_dir=1,
                                flap=flap))
        if not in_flight and idle >= self.hyst and s.fleet > self.MIN:
            victim_draining = self.victim_draining and \
                s.spot == "draining"
            flap = s.flap or (s.last_dir == 1 and since < self.window)
            label = (f"autoscaler tick: {self.hyst} idle window(s) -> "
                     f"decide scale-DOWN"
                     + (" (MUTATION: victim is the already-draining "
                        "worker)" if victim_draining else
                        " (victim: least-loaded accepting worker)"))
            return (label,
                    ns._replace(rec=("down", "decide", s.epoch,
                                     victim_draining),
                                hot=0, idle=0, since=0, last_dir=-1,
                                flap=flap))
        return "autoscaler tick: observe (no decision)", ns

    def actions(self, s: AutoState):
        out = []
        # -- load / environment ---------------------------------------------
        if s.spikes_left > 0 and not s.pressure:
            out.append(("flash crowd arrives (queue depth / p99 breach "
                        "the SLO bound)",
                        s._replace(pressure=True,
                                   spikes_left=s.spikes_left - 1)))
        if s.recedes_left > 0 and s.pressure:
            out.append(("load recedes (queues empty, fleet idle)",
                        s._replace(pressure=False,
                                   recedes_left=s.recedes_left - 1)))
        # -- worker-side faults ----------------------------------------------
        if s.preempts_left > 0 and s.spot == "none" and s.fleet > 1:
            out.append((
                f"fault: spot preemption notice — a worker announces "
                f"`{kv_keys.drain('host', 0)}` and stops accepting",
                s._replace(spot="draining", fleet=s.fleet - 1,
                           preempts_left=s.preempts_left - 1)))
        if s.spot == "draining":
            out.append(("spot-drained worker finishes its accepted "
                        "requests and exits 0",
                        s._replace(spot="done")))
            if s.kills_left > 0:
                out.append((
                    "fault: host dies mid-drain (draining worker "
                    "SIGKILLed; router re-routes its in-flight)",
                    s._replace(spot="done",
                               kills_left=s.kills_left - 1)))
        if s.spot == "done":
            out.append(("driver reaps the spot drain (clean departure)",
                        s._replace(spot="none")))
        if s.kills_left > 0 and s.fleet > 0:
            out.append((
                "fault: accepting worker SIGKILLed (no notice)",
                s._replace(fleet=s.fleet - 1,
                           kills_left=s.kills_left - 1)))
        # -- autoscaler + driver protocol (only while the driver lives) ------
        if not s.crashed:
            out.append(self._tick(s))
            out.extend(self._protocol(s))
        # -- driver crash / recovery -----------------------------------------
        if s.crashes_left > 0 and not s.crashed:
            lingering = s.rec is not None and s.rec[1] != "ack"
            out.append((
                "fault: driver crashes (supervisor presumes it dead; the "
                "process lingers)" if lingering else
                "fault: driver crashes",
                s._replace(crashed=True, old_alive=lingering,
                           old_rec=s.rec if lingering else None,
                           crashes_left=s.crashes_left - 1)))
        if s.crashed:
            new_epoch = s.kv_epoch + 1
            rec = s.rec
            label = (f"supervisor respawns the driver (epoch "
                     f"{s.kv_epoch} -> {new_epoch})")
            if rec is not None and rec[1] != "ack":
                rec = (rec[0], rec[1], new_epoch, rec[3])
                label += (f"; recovery RESUMES the {rec[0]} decision at "
                          f"state {rec[1]} instead of re-deciding")
            out.append((label, s._replace(
                crashed=False, epoch=new_epoch, kv_epoch=new_epoch,
                rec=rec)))
        # the fenced-out predecessor tries to finish its old decision
        if s.old_alive and s.old_rec is not None and \
                s.old_rec[2] < s.kv_epoch:
            out.append(self._stale_write(s))
        return out

    def _protocol(self, s: AutoState):
        """The driver advancing the in-flight decision record."""
        out = []
        if s.rec is None:
            return out
        action, state, epoch, victim_draining = s.rec
        if state == "decide" and action == "up":
            out.append((
                "driver acts on the decision: spawn a worker "
                "(record -> resize)",
                s._replace(rec=(action, "resize", epoch,
                                victim_draining))))
        if state == "decide" and action == "down":
            if victim_draining:
                # MUTATION path: the victim already received a spot
                # notice; a REPEATED notice force-exits immediately
                # (preempt.py), dropping everything it had accepted
                out.append((
                    "driver delivers a SECOND preemption notice to the "
                    "already-draining victim: it force-exits, acked "
                    "requests lost (record -> drain)",
                    s._replace(rec=(action, "drain", epoch, True),
                               spot="done", lost_acked=True)))
            elif s.fleet > 0:
                out.append((
                    "driver delivers the preemption notice: victim "
                    "stops accepting and drains (record -> drain)",
                    s._replace(rec=(action, "drain", epoch, False),
                               auto="draining", fleet=s.fleet - 1)))
        if state == "drain":
            if s.auto == "draining":
                out.append(("scale-down victim finishes its accepted "
                            "requests and exits 0",
                            s._replace(auto="done")))
            if s.auto == "done" or (victim_draining and s.spot == "done"):
                out.append((
                    "driver resize removes the drained slot "
                    "(record -> resize)",
                    s._replace(rec=(action, "resize", epoch,
                                    victim_draining),
                               auto="none")))
        if state == "resize":
            if action == "up":
                fleet = s.fleet + 1
                out.append((
                    "spawned worker joins the fleet; decision acked "
                    f"(`{kv_keys.autoscale_event(1)}` audit record)",
                    s._replace(fleet=fleet,
                               rec=(action, "ack", epoch,
                                    victim_draining),
                               unclamped=s.unclamped or
                               fleet > self.MAX)))
            else:
                out.append((
                    "scale-down resize complete; decision acked "
                    f"(`{kv_keys.autoscale_event(1)}` audit record)",
                    s._replace(rec=(action, "ack", epoch,
                                    victim_draining))))
        return out

    def _stale_write(self, s: AutoState):
        action, state, old_epoch, _ = s.old_rec
        outcome, _new = rules.admit_epoch(s.kv_epoch, old_epoch)
        if outcome == rules.FENCED and not self.no_epoch_fence:
            return (
                f"kv 409s the lingering driver's "
                f"`{kv_keys.autoscale_decision()}` write (offered epoch "
                f"{old_epoch} < current {s.kv_epoch}); it stands down",
                s._replace(old_alive=False))
        fleet = s.fleet + 1 if action == "up" else max(0, s.fleet - 1)
        return (
            f"lingering driver applies its stale {action} decision "
            f"(MUTATION: epoch fence skipped) — the fleet resizes twice "
            f"for one decision",
            s._replace(old_alive=False, fleet=fleet, stale_applied=True,
                       unclamped=s.unclamped or fleet > self.MAX or
                       fleet < 0))

    @property
    def invariants(self) -> List[Invariant]:
        return [
            Invariant(
                "no_flap",
                "no opposite-direction decisions inside one hysteresis "
                "window (a one-window spike or dip never reverses the "
                "fleet — the loop cannot oscillate)",
                lambda s: not s.flap),
            Invariant(
                "no_acked_request_loss",
                "scale-down never selects an already-draining worker "
                "(the repeated preemption notice would force-exit it and "
                "drop the requests it had accepted)",
                lambda s: not s.lost_acked),
            Invariant(
                "stale_epoch_decision_fenced",
                "a fenced-out (pre-crash) driver's scaling decision "
                "never mutates the fleet after recovery — the recovered "
                "driver resumes the record; the old one is 409'd",
                lambda s: not s.stale_applied),
            Invariant(
                "fleet_within_clamps",
                "no resize the autoscaler performs leaves the "
                "[min_workers, max_workers] interval",
                lambda s: not s.unclamped),
        ]


# ===========================================================================
# Replicated control plane: leader lease, majority replication, election
# ===========================================================================

class ReplicaState(NamedTuple):
    believes: tuple    # per replica: believes it holds a valid lease
    epoch: tuple       # per replica: adopted control epoch (term)
    log: tuple         # per replica: tuple of WAL entries, each a
    #                    (term, id) pair — the term stamps the entry
    #                    with the epoch it was appended under (the Raft
    #                    log-matching state); id > 0 is a client write,
    #                    id == -e is the lease record of the epoch-e
    #                    grant (appended at term e)
    alive: tuple       # per replica: process up
    part: tuple        # per replica: partitioned off from the others
    lease_live: bool   # the current grant's real-time window is open
    #                    (followers must wait it out before electing)
    acked: frozenset   # write ids acked to the client
    regressed: bool    # a grant's epoch failed to exceed every prior one
    writes_left: int
    retries_left: int
    kills_left: int
    parts_left: int
    heals_left: int
    elects_left: int


class ReplicaSpec(Spec):
    """Three KV replicas (``runner/replica_kv.py``), one client write +
    one retry of it (same idempotency token), modeled at the grain the
    protocol argues at: lease grants, majority-acked appends, elections,
    rejoin resync. Faults: one replica kill, one partition (isolating
    one replica), one heal. ``lease_live`` is the bounded-clock
    abstraction — while True, no correct voter grants (it is still
    inside the lease wait window); expiry requires the leaseholder dead
    or partitioned (a healthy leader keeps renewing), and the expiring
    leader **self-fences** in the same instant (its own write-path lease
    check — exactly what ``stale_lease_accepts_write`` removes).

    The election rule is the shared :func:`rules.vote_grants` /
    :func:`rules.majority` pair the real vote handler uses — the Raft
    up-to-date order over term-stamped log entries — and the lease
    record the winner replicates is IN the model (a log entry): it is
    load-bearing — a deposed leader carries at most one un-acked
    suffix record (it self-fences on the first majority-refused write),
    appended at its OLD term, while the grant record puts the winner's
    new term at the top of every majority log — which is why
    highest-(epoch, last-term, WAL-length) never elects a leader
    missing an acked write, even against an equal-*length* diverged
    rival."""

    N = 3
    WRITE = 1  # the one modeled client write id

    def __init__(self, stale_lease_accepts_write: bool = False,
                 election_without_majority: bool = False,
                 retry_double_apply: bool = False):
        super().__init__(name="replica", mutations=tuple(
            m for m, on in [
                ("stale_lease_accepts_write", stale_lease_accepts_write),
                ("election_without_majority", election_without_majority),
                ("retry_double_apply", retry_double_apply)] if on))
        self.stale_lease = stale_lease_accepts_write
        self.minority_elect = election_without_majority
        self.double_apply = retry_double_apply

    def initial(self) -> ReplicaState:
        n = self.N
        return ReplicaState(
            believes=(False,) * n, epoch=(0,) * n, log=((),) * n,
            alive=(True,) * n, part=(False,) * n, lease_live=False,
            acked=frozenset(), regressed=False,
            writes_left=1, retries_left=1, kills_left=1, parts_left=1,
            heals_left=1, elects_left=2)

    # -- helpers --------------------------------------------------------------

    def _reachable(self, s: ReplicaState, i: int) -> List[int]:
        """Peers replica i can talk to: alive, and on the same side of
        the (single modeled) partition."""
        return [j for j in range(self.N)
                if j != i and s.alive[j] and s.part[j] == s.part[i]]

    @staticmethod
    def _max_holder_epoch(s: ReplicaState) -> int:
        """Highest epoch any lease was ever granted at — recoverable
        from the persisted lease records, so not extra state."""
        return max([0] + [-eid for log in s.log
                          for _t, eid in log if eid < 0])

    @staticmethod
    def _last_term(log: tuple) -> int:
        """Term of the last WAL entry — the replica's position in the
        Raft up-to-date order (``rules.vote_grants``)."""
        return log[-1][0] if log else 0

    # -- transitions ----------------------------------------------------------

    def actions(self, s: ReplicaState):
        out = []
        for c in range(self.N):
            if s.alive[c] and not s.believes[c] and s.elects_left > 0:
                act = self._elect(s, c)
                if act is not None:
                    out.append(act)
        for i in range(self.N):
            if s.believes[i] and s.alive[i]:
                if s.writes_left > 0:
                    out.append(self._write(s, i, retry=False))
                if s.retries_left > 0 and s.writes_left == 0:
                    out.append(self._write(s, i, retry=True))
        holder_blocked = all(
            not s.believes[i] or not s.alive[i] or s.part[i]
            for i in range(self.N))
        if s.lease_live and holder_blocked:
            believes = s.believes if self.stale_lease \
                else (False,) * self.N
            label = ("lease expires; the unreachable leader self-fences "
                     "on its own expiry check"
                     if not self.stale_lease else
                     "lease expires (MUTATION: the leader's write-path "
                     "expiry check is gone — it keeps accepting)")
            out.append((label, s._replace(lease_live=False,
                                          believes=believes)))
        for i in range(self.N):
            if s.alive[i] and s.kills_left > 0:
                out.append((
                    f"fault: replica{i} SIGKILLed"
                    + (" (the leaseholder)" if s.believes[i] else ""),
                    s._replace(alive=_rep(s.alive, i, False),
                               believes=_rep(s.believes, i, False),
                               kills_left=s.kills_left - 1)))
            if s.alive[i] and not s.part[i] and s.parts_left > 0:
                out.append((
                    f"fault: replica{i} partitioned off",
                    s._replace(part=_rep(s.part, i, True),
                               parts_left=s.parts_left - 1)))
        if any(s.part) and s.heals_left > 0:
            out.append((
                "partition heals (links restored)",
                s._replace(part=(False,) * self.N,
                           heals_left=s.heals_left - 1)))
        resync = self._resync(s)
        if resync is not None:
            out.append(resync)
        return out

    def _elect(self, s: ReplicaState, c: int):
        electorate = self._reachable(s, c)
        proposed = max([s.epoch[c]] + [s.epoch[j] for j in electorate]) + 1
        votes = 1  # self
        granting = []
        for j in electorate:
            heard = s.lease_live or s.believes[j]
            if rules.vote_grants(s.epoch[j], self._last_term(s.log[j]),
                                 len(s.log[j]), proposed,
                                 self._last_term(s.log[c]),
                                 len(s.log[c]), heard):
                votes += 1
                granting.append(j)
        quorum = 1 if self.minority_elect else rules.majority(self.N)
        if votes < quorum:
            return None  # a failed solicitation changes nothing
        regressed = s.regressed or proposed <= self._max_holder_epoch(s)
        epoch = s.epoch
        log = s.log
        # the winner persists + replicates the lease record (its first
        # majority-acked append); granting voters adopt the new epoch
        for j in [c] + granting:
            epoch = _rep(epoch, j, proposed)
            log = _rep(log, j, s.log[j] + ((proposed, -proposed),))
        label = (f"replica{c} elected: epoch {proposed}, "
                 f"{votes}/{self.N} votes; lease record replicated")
        if self.minority_elect and votes < rules.majority(self.N):
            label = (f"replica{c} elects ITSELF (MUTATION: {votes} "
                     f"vote(s), no majority) at epoch {proposed}")
        return label, s._replace(
            believes=_rep(s.believes, c, True), epoch=epoch, log=log,
            lease_live=True, regressed=regressed,
            elects_left=s.elects_left - 1)

    def _write(self, s: ReplicaState, i: int, retry: bool):
        w = self.WRITE
        entry = (s.epoch[i], w)  # appended under the writer's term
        applied = any(eid == w for _t, eid in s.log[i])
        budget = {"retries_left": s.retries_left - 1} if retry \
            else {"writes_left": s.writes_left - 1}
        tag = "retried " if retry else ""
        if retry and not self.double_apply and applied:
            # the (client, seq) token was already applied here — dedupe
            # drops the replay and re-acks
            return (f"replica{i} dedupes the retried write (token "
                    f"already applied)",
                    s._replace(acked=s.acked | {w}, **budget))
        mutated = retry and self.double_apply and applied
        reachable = self._reachable(s, i)
        refused = any(s.epoch[j] > s.epoch[i] for j in reachable)
        if refused:
            # a follower on a newer term 409s the forward: the deposed
            # leader self-fences; its local append is the un-acked
            # suffix resync later truncates
            return (f"replica{i}'s {tag}write forward is 409'd by a "
                    f"newer-term follower; it self-fences",
                    s._replace(log=_rep(s.log, i, s.log[i] + (entry,)),
                               believes=_rep(s.believes, i, False),
                               **budget))
        # only a follower whose log matches the leader's accepts the
        # append (the real prev-(seq, term) check — term-stamped
        # entries make equal-length diverged logs visible); a diverged
        # one answers "resync me" and does NOT ack this round
        accepting = [j for j in reachable if s.log[j] == s.log[i]]
        if 1 + len(accepting) < rules.majority(self.N):
            return (f"replica{i}'s {tag}write cannot reach a follower "
                    f"majority; it self-fences un-acked",
                    s._replace(log=_rep(s.log, i, s.log[i] + (entry,)),
                               believes=_rep(s.believes, i, False),
                               **budget))
        log = _rep(s.log, i, s.log[i] + (entry,))
        epoch = s.epoch
        for j in accepting:
            log = _rep(log, j, s.log[j] + (entry,))
            epoch = _rep(epoch, j, max(s.epoch[j], s.epoch[i]))
        label = (f"replica{i} commits the {tag}write to a majority "
                 f"({1 + len(accepting)}/{self.N}); acked")
        if mutated:
            label = (f"replica{i} re-appends the retried write "
                     f"(MUTATION: dedupe token check skipped); acked")
        return label, s._replace(
            log=log, epoch=epoch, acked=s.acked | {w}, lease_live=True,
            **budget)

    def _resync(self, s: ReplicaState):
        """The leader's heartbeat notices a reachable diverged follower
        and ships it full state (the WAL-divergence repair path: the
        follower's un-majority-committed suffix is truncated, loudly).
        Unbudgeted — it converges (the guard disables once logs match),
        like the real ticker retriggering until the fleet agrees."""
        holder = next((i for i in range(self.N)
                       if s.believes[i] and s.alive[i]), None)
        if holder is None:
            return None
        diverged = [j for j in self._reachable(s, holder)
                    if s.log[j] != s.log[holder]]
        if not diverged:
            return None
        log, epoch = s.log, s.epoch
        for j in diverged:
            log = _rep(log, j, s.log[holder])
            epoch = _rep(epoch, j, max(s.epoch[j], s.epoch[holder]))
        return (f"leader resyncs diverged replica(s) "
                f"{diverged} (un-committed WAL suffixes truncated)",
                s._replace(log=log, epoch=epoch))

    @property
    def invariants(self) -> List[Invariant]:
        def one_leaseholder(s: ReplicaState) -> bool:
            return sum(s.believes) <= 1

        def no_acked_loss(s: ReplicaState) -> bool:
            return all(any(eid == w for _t, eid in s.log[i])
                       for i in range(self.N) if s.believes[i]
                       for w in s.acked)

        def applied_once(s: ReplicaState) -> bool:
            return all(sum(eid == self.WRITE for _t, eid in log) <= 1
                       for log in s.log)

        return [
            Invariant(
                "at_most_one_leaseholder",
                "no instant has two replicas both believing they hold "
                "the lease (two writers accepting = split brain)",
                one_leaseholder),
            Invariant(
                "no_acked_write_loss",
                "every write acked to the client is present in the "
                "current leaseholder's WAL — elections can never seat a "
                "leader missing a majority-committed record",
                no_acked_loss),
            Invariant(
                "epoch_monotonic_across_elections",
                "every lease grant's epoch strictly exceeds every "
                "earlier grant's (the fencing token never regresses)",
                lambda s: not s.regressed),
            Invariant(
                "write_applied_at_most_once",
                "a retried client op lands at most once in any "
                "replica's WAL (the idempotency-token dedupe)",
                applied_once),
        ]


# ===========================================================================
# Registries
# ===========================================================================

# ===========================================================================
# Serving fast path: block-paged KV cache ownership
# ===========================================================================

class PagedState(NamedTuple):
    free: int        # unowned pool blocks
    resident: bool   # the shared prefix block is resident in the pool
    published: bool  # it was published at least once (sticky — the hash
    #                  table entry the stale-reuse mutation consults)
    slots: tuple     # per request slot: (phase, charged, bound, shref)
    #                  phase: 0 none, 1 queued, 2 running
    qexp_left: int   # queued-expiry fault budget
    rexp_left: int   # running-expiry fault budget
    kills_left: int  # chaos-kill fault budget


_PG_NONE, _PG_QUEUED, _PG_RUNNING = 0, 1, 2


class PagedCacheSpec(Spec):
    """Block ownership in ``serve/kv_cache.py``: two request slots over a
    minimal pool (3 blocks) with one shareable prefix block.

    Every request needs 2 blocks (1 prefix + 1 private tail); admission
    *charges* the pool (or increfs the resident shared prefix and
    charges 1), the decode loop *binds* lazily, prefill *publishes* the
    prefix block as shared CoW (the publisher's private charge converts
    — conservation holds exactly), and teardown frees at a step
    boundary. Faults: queued expiry (must release, never having bound),
    running expiry (frees at the boundary where the partial output
    returns), a chaos kill mid-decode (the re-route teardown path), an
    LRU eviction of the zero-ref shared block, and drain. Mutations
    re-introduce the two seeded hazards: ``double_free_running_expiry``
    (the boundary teardown frees the charge twice — once at expiry, once
    again at finish) and ``stale_prefix_reuse`` (admission consults the
    prefix hash table without checking residency, increfing a block the
    LRU already evicted — use-after-free)."""

    POOL = 3
    SLOTS = 2

    def __init__(self, double_free_running_expiry: bool = False,
                 stale_prefix_reuse: bool = False):
        super().__init__(name="paged_cache", mutations=tuple(
            m for m, on in [("double_free_running_expiry",
                             double_free_running_expiry),
                            ("stale_prefix_reuse",
                             stale_prefix_reuse)] if on))
        self.double_free = double_free_running_expiry
        self.stale_reuse = stale_prefix_reuse
        # the model is the minimal pool exhibiting every hazard; the
        # shipped pool is configured by these registry knobs (defaults
        # asserted real so the spec can't drift from the code)
        from horovod_tpu.common.env_registry import REGISTRY
        assert int(REGISTRY["HOROVOD_SERVE_KV_POOL_BLOCKS"].default) > 0
        assert int(REGISTRY["HOROVOD_SERVE_KV_BLOCK_TOKENS"].default) > 0

    def initial(self) -> PagedState:
        return PagedState(
            free=self.POOL, resident=False, published=False,
            slots=((_PG_NONE, 0, 0, 0),) * self.SLOTS,
            qexp_left=1, rexp_left=1, kills_left=1)

    # -- helpers --------------------------------------------------------------

    @staticmethod
    def _refs(s: PagedState) -> int:
        return sum(sl[3] for sl in s.slots)

    def _teardown(self, s: PagedState, i: int, label: str,
                  double: bool = False) -> Tuple[str, PagedState]:
        ch = s.slots[i][1]
        freed = ch * (2 if double else 1)
        return (label, s._replace(
            free=s.free + freed,
            slots=_rep(s.slots, i, (_PG_NONE, 0, 0, 0))))

    # -- transitions ----------------------------------------------------------

    def actions(self, s: PagedState):
        out = []
        for i, (ph, ch, bd, sh) in enumerate(s.slots):
            if ph == _PG_NONE:
                # admission: charge 2 private blocks, or incref the
                # resident shared prefix and charge 1. The stale-reuse
                # mutation consults the hash table WITHOUT the residency
                # check — the entry may point at an evicted block.
                hit = s.published if self.stale_reuse else s.resident
                need = 1 if hit else 2
                if s.free >= need:
                    tag = " (MUTATION: stale hash entry, block evicted)" \
                        if hit and not s.resident else \
                        (" (shared-prefix hit, incref)" if hit else "")
                    out.append((
                        f"slot {i}: admit charges {need} block(s)"
                        f"{tag}",
                        s._replace(free=s.free - need,
                                   slots=_rep(s.slots, i,
                                              (_PG_QUEUED, need, 0,
                                               1 if hit else 0)))))
            elif ph == _PG_QUEUED:
                out.append((
                    f"slot {i}: scheduled into the batch",
                    s._replace(slots=_rep(s.slots, i,
                                          (_PG_RUNNING, ch, bd, sh)))))
                if s.qexp_left > 0:
                    out.append(self._teardown(
                        s._replace(qexp_left=s.qexp_left - 1), i,
                        f"slot {i}: deadline passes while QUEUED — "
                        f"release the charge (never bound a block)"))
            elif ph == _PG_RUNNING:
                if bd < ch:
                    # decode step: bind the charged blocks; prefill
                    # publishes the prefix block as shared CoW (the
                    # publisher's private charge converts to the shared
                    # population — pool conservation is exact)
                    if sh == 0 and not s.resident:
                        out.append((
                            f"slot {i}: prefill step binds + PUBLISHES "
                            f"the prefix block (private -> shared CoW)",
                            s._replace(
                                resident=True, published=True,
                                slots=_rep(s.slots, i,
                                           (_PG_RUNNING, ch - 1, ch - 1,
                                            1)))))
                    else:
                        out.append((
                            f"slot {i}: decode step binds {ch} block(s)",
                            s._replace(slots=_rep(s.slots, i,
                                                  (_PG_RUNNING, ch, ch,
                                                   sh)))))
                else:
                    out.append(self._teardown(
                        s, i,
                        f"slot {i}: completes — frees {ch} charged "
                        f"block(s) at the step boundary, decref shared"))
                    if s.rexp_left > 0:
                        lbl = (f"slot {i}: deadline passes mid-decode — "
                               f"partial output returned, {ch} block(s) "
                               f"freed at the step boundary")
                        if self.double_free:
                            lbl += (" (MUTATION: freed again at finish "
                                    "— double free)")
                        out.append(self._teardown(
                            s._replace(rexp_left=s.rexp_left - 1), i,
                            lbl, double=self.double_free))
                    if s.kills_left > 0:
                        out.append(self._teardown(
                            s._replace(kills_left=s.kills_left - 1), i,
                            f"slot {i}: CHAOS KILL mid-decode — router "
                            f"re-routes, teardown frees {ch} block(s)"))
        if s.resident and self._refs(s) == 0:
            out.append((
                "LRU evicts the zero-ref shared prefix block",
                s._replace(resident=False, free=s.free + 1)))
        active = [i for i, sl in enumerate(s.slots)
                  if sl[0] != _PG_NONE]
        if active:
            ns = s
            for i in active:
                _lbl, ns = self._teardown(
                    ns, i, "")
            out.append((
                f"drain: slot(s) {active} finish and free their charges",
                ns))
        return out

    @property
    def invariants(self) -> List[Invariant]:
        pool = self.POOL

        def conserved(s: PagedState) -> bool:
            return s.free + sum(sl[1] for sl in s.slots) + \
                (1 if s.resident else 0) == pool

        return [
            Invariant(
                "charge_free_balance",
                "pool conservation: free + charged(private) + "
                "resident(shared) == pool at every step boundary — a "
                "double free (or a leak) breaks the ledger",
                conserved),
            Invariant(
                "no_use_after_free",
                "no live request holds a reference to an evicted shared "
                "block (admission must re-check residency, not just the "
                "hash table)",
                lambda s: self._refs(s) == 0 or s.resident),
            Invariant(
                "queued_never_binds",
                "a queued request owns charged capacity only — it never "
                "binds a physical block (the expiry split: queued "
                "expiry releases, it has nothing to free)",
                lambda s: all(sl[2] == 0 for sl in s.slots
                              if sl[0] == _PG_QUEUED)),
            Invariant(
                "no_aliasing",
                "a block has one owner: bound never exceeds charged and "
                "the free count never goes negative (aliasing between "
                "live requests shows up as either)",
                lambda s: s.free >= 0 and
                all(sl[2] <= sl[1] for sl in s.slots)),
        ]


# ===========================================================================
# Tiered telemetry scrape (per-host aggregator + driver fallback)
# ===========================================================================

class ScrapeState(NamedTuple):
    c: int                # the rank's live counter value (this incarnation)
    avail: int            # increments that occurred while a driver
    #                       baseline existed (upper bound for T)
    a_val: Optional[int]  # aggregator's cached snapshot of c (None: none)
    a_stale: bool         # payload older than the staleness bound (dead)
    a_old: bool           # payload predates the driver's last direct
    #                       consume (age-fresh but window-regressed)
    b: Optional[int]      # driver baseline for the rank (metrics_prev)
    b_gen: int            # generation the baseline was established in
    T: int                # driver-visible accumulated counter delta
    gen: int              # driver topology generation
    stale_baseline_used: bool  # a heartbeat diffed against a baseline
    #                            from an older generation (PR-7 class)
    incs_left: int
    deaths_left: int
    gens_left: int


class ScrapeSpec(Spec):
    """One host, one rank, both scrape tiers (ISSUE 18).

    The rank owns a monotonic counter; the per-host aggregator caches a
    snapshot of it (``/agg.json``); the driver's heartbeat consumes the
    host through EXACTLY one path per beat — the aggregator when its
    payload is fresh and not window-regressed, the direct per-rank
    scrape otherwise — diffing into one shared baseline. Faults: the
    aggregator crashes (payload goes stale), and a generation change
    restarts the rank (counter back to zero) while the driver clears
    its baselines exactly once (``_rebalance``).

    Invariants:

    - ``no_double_count`` — the driver-visible accumulated delta never
      exceeds the true increment count. Killed by
      ``double_count_on_fallback`` (one heartbeat consumes the host via
      BOTH paths against the same baseline) and by
      ``consume_stale_window`` (an age-fresh aggregator payload that
      predates the last direct consume regresses the baseline, and the
      next window re-counts the difference — the hazard the
      ``TieredScrape`` per-host window floor exists to stop);
    - ``baseline_reset_on_generation`` — no heartbeat ever diffs against
      a baseline established under an older generation. Killed by
      ``skip_baseline_reset`` (the generation change keeps the baseline
      maps — the PR-7 stale-baseline bug via the new tier).

    Monotonicity of the driver-visible total is structural (deltas are
    only ever added when positive), so it needs no separate invariant.
    """

    def __init__(self, double_count_on_fallback: bool = False,
                 skip_baseline_reset: bool = False,
                 consume_stale_window: bool = False,
                 incs: int = 3, deaths: int = 1, gens: int = 1):
        super().__init__(name="scrape", mutations=tuple(
            m for m, on in [
                ("double_count_on_fallback", double_count_on_fallback),
                ("skip_baseline_reset", skip_baseline_reset),
                ("consume_stale_window", consume_stale_window)] if on))
        self.double_count_on_fallback = double_count_on_fallback
        self.skip_baseline_reset = skip_baseline_reset
        self.consume_stale_window = consume_stale_window
        self.incs = incs
        self.deaths = deaths
        self.gens = gens

    def initial(self) -> ScrapeState:
        return ScrapeState(
            c=0, avail=0, a_val=None, a_stale=False, a_old=False,
            b=None, b_gen=0, T=0, gen=0, stale_baseline_used=False,
            incs_left=self.incs, deaths_left=self.deaths,
            gens_left=self.gens)

    # one consume = TieredScrape._consume_rank: establish or diff the
    # shared baseline (the same code path for both tiers).  Establishing
    # from a snapshot v absorbs increments [0, v] forever, but anything
    # the rank did above v is countable later — credit it to ``avail``
    # (matters when establishing from an aggregator payload older than
    # the rank's live counter).
    def _consume(self, s: ScrapeState, v: int) -> ScrapeState:
        if s.b is None:
            return s._replace(b=v, b_gen=s.gen, avail=s.avail + (s.c - v))
        stale = s.stale_baseline_used or s.b_gen != s.gen
        delta = v - s.b if v > s.b else 0
        return s._replace(b=v, T=s.T + delta, stale_baseline_used=stale)

    def actions(self, s: ScrapeState):
        out = []
        if s.incs_left > 0:
            out.append(("rank.inc", s._replace(
                c=s.c + 1, avail=s.avail + (1 if s.b is not None else 0),
                incs_left=s.incs_left - 1)))
        # aggregator refresh: snapshot the rank NOW; a fresh window
        # clears both the staleness and the regression marks
        out.append(("agg.refresh", s._replace(
            a_val=s.c, a_stale=False, a_old=False)))
        if s.deaths_left > 0 and s.a_val is not None and not s.a_stale:
            out.append(("fault: aggregator crashes mid-heartbeat "
                        "(payload ages past the staleness bound)",
                        s._replace(a_stale=True,
                                   deaths_left=s.deaths_left - 1)))
        # driver heartbeat — exactly one path per beat in the clean spec
        agg_usable = s.a_val is not None and not s.a_stale and \
            (not s.a_old or self.consume_stale_window)
        if agg_usable:
            out.append(("driver.heartbeat(agg)", self._consume(s, s.a_val)))
        # direct scrape: the mandatory path when the aggregator tier is
        # unusable, and always reachable via a transient agg-fetch
        # failure (KV miss / connection refused) even when it is.
        # Either way, any still-cached aggregator payload now predates
        # this consume — window-regressed from here on (the real
        # TieredScrape records this as the per-host window floor).
        nxt = self._consume(s, s.c)
        if nxt.a_val is not None:
            nxt = nxt._replace(a_old=True)
        out.append(("driver.heartbeat(direct fallback)", nxt))
        if self.double_count_on_fallback and agg_usable \
                and s.b is not None:
            # seeded bug: the fallback leg runs after the aggregator leg
            # in the SAME heartbeat, both diffing the baseline read at
            # heartbeat start
            d1 = s.a_val - s.b if s.a_val > s.b else 0
            d2 = s.c - s.b if s.c > s.b else 0
            stale = s.stale_baseline_used or s.b_gen != s.gen
            out.append(("driver.heartbeat(BOTH paths)", s._replace(
                b=s.c, T=s.T + d1 + d2, stale_baseline_used=stale)))
        if s.gens_left > 0:
            # elastic resize: the rank restarts (counter from zero), the
            # aggregator's old payload dies with its worker, and the
            # driver clears its baselines exactly once (_rebalance) —
            # unless the seeded PR-7 mutant skips the clear
            nxt = s._replace(c=0, a_val=None, a_stale=False, a_old=False,
                             gen=s.gen + 1, gens_left=s.gens_left - 1)
            if not self.skip_baseline_reset:
                nxt = nxt._replace(b=None, b_gen=nxt.gen)
            out.append(("driver.rebalance(generation change)", nxt))
        return out

    @property
    def invariants(self) -> List[Invariant]:
        return [
            Invariant(
                "no_double_count",
                "the driver-visible accumulated counter delta never "
                "exceeds the increments that actually occurred while a "
                "baseline existed — a rank is consumed through exactly "
                "one scrape path per window",
                lambda s: s.T <= s.avail),
            Invariant(
                "baseline_reset_on_generation",
                "no heartbeat diffs against a baseline established "
                "under an older generation (the generation change "
                "clears the shared baseline maps exactly once)",
                lambda s: not s.stale_baseline_used),
        ]


# ===========================================================================
# Durable event journal (common/journal.py)
# ===========================================================================

class JournalState(NamedTuple):
    buffered: tuple        # (comp, seq) appended, not yet durable
    active_durable: tuple  # flushed records still in the active segment
    closed_segs: tuple     # closed segments, oldest first (tuples of events)
    acked: frozenset       # events whose emitter was told "recorded"
    retired: frozenset     # retention-deleted (closed segments only)
    lost: frozenset        # gone without ever becoming durable
    crashed: bool          # the writer process died (buffer gone)
    next_seq: tuple        # per-component next sequence number
    appends_left: int
    rotations_left: int
    crashes_left: int


class JournalSpec(Spec):
    """One journal writer appending events for two components, with
    segment rotation, closed-segment retention, and a crash that loses
    whatever is buffered but not flushed. The durable order — closed
    segments oldest-first, then the active segment's flushed records —
    is exactly what :func:`common.journal.iter_journal` replays and what
    ``hvd-check --conformance``'s journal auditor checks on real
    artifacts.

    Mutations re-introduce the three ways a journal silently lies:
    acking before the flush (a crash then loses an acked event), seq
    reset at rotation (replay order becomes ambiguous across segments),
    and rotation closing the active segment without flushing its tail
    (durable-looking records evaporate with no crash at all)."""

    COMPONENTS = ("driver", "serve")

    def __init__(self, appends: int = 4, rotations: int = 2,
                 crashes: int = 1, keep: int = 1,
                 ack_before_flush: bool = False,
                 seq_reset_on_rotate: bool = False,
                 rotate_skip_flush: bool = False):
        super().__init__(name="journal", mutations=tuple(
            m for m, on in [("ack_before_flush", ack_before_flush),
                            ("seq_reset_on_rotate", seq_reset_on_rotate),
                            ("rotate_skip_flush", rotate_skip_flush)]
            if on))
        self.appends = appends
        self.rotations = rotations
        self.crashes = crashes
        self.keep = keep  # retention: closed segments retained
        self.ack_before_flush = ack_before_flush
        self.seq_reset_on_rotate = seq_reset_on_rotate
        self.rotate_skip_flush = rotate_skip_flush

    def initial(self) -> JournalState:
        return JournalState(
            buffered=(), active_durable=(), closed_segs=(),
            acked=frozenset(), retired=frozenset(), lost=frozenset(),
            crashed=False, next_seq=(0,) * len(self.COMPONENTS),
            appends_left=self.appends, rotations_left=self.rotations,
            crashes_left=self.crashes)

    @staticmethod
    def _durable_order(s: JournalState) -> tuple:
        out: tuple = ()
        for seg in s.closed_segs:
            out += seg
        return out + s.active_durable

    def actions(self, s: JournalState):
        out = []
        if s.appends_left > 0 and not s.crashed:
            for ci, comp in enumerate(self.COMPONENTS):
                seq = s.next_seq[ci]
                ev = (comp, seq)
                nxt = s._replace(
                    buffered=s.buffered + (ev,),
                    next_seq=_rep(s.next_seq, ci, seq + 1),
                    appends_left=s.appends_left - 1)
                if self.ack_before_flush:
                    # the seeded lie: the emitter hears "recorded"
                    # while the record is still a volatile buffer
                    nxt = nxt._replace(acked=nxt.acked | {ev})
                out.append((f"append({comp}, seq={seq})", nxt))
        if s.buffered and not s.crashed:
            out.append(("flush(ack)", s._replace(
                buffered=(),
                active_durable=s.active_durable + s.buffered,
                acked=s.acked | frozenset(s.buffered))))
        if s.rotations_left > 0 and not s.crashed and \
                (s.active_durable or s.buffered):
            if self.rotate_skip_flush:
                # seeded bug: close the active segment without flushing
                # its tail — the buffered records just evaporate
                nxt = s._replace(
                    buffered=(), lost=s.lost | frozenset(s.buffered),
                    active_durable=(),
                    closed_segs=s.closed_segs + (s.active_durable,),
                    rotations_left=s.rotations_left - 1)
            else:
                nxt = s._replace(
                    buffered=(), active_durable=(),
                    acked=s.acked | frozenset(s.buffered),
                    closed_segs=s.closed_segs +
                    (s.active_durable + s.buffered,),
                    rotations_left=s.rotations_left - 1)
            if self.seq_reset_on_rotate:
                nxt = nxt._replace(
                    next_seq=(0,) * len(self.COMPONENTS))
            out.append(("rotate(flush+close)", nxt))
        if len(s.closed_segs) > self.keep:
            # retention prunes oldest CLOSED segments only; the active
            # segment is structurally out of reach
            out.append(("retention.delete(oldest closed)", s._replace(
                closed_segs=s.closed_segs[1:],
                retired=s.retired | frozenset(s.closed_segs[0]))))
        if s.crashes_left > 0 and s.buffered and not s.crashed:
            out.append(("crash(buffer lost)", s._replace(
                buffered=(), lost=s.lost | frozenset(s.buffered),
                crashed=True, crashes_left=s.crashes_left - 1,
                appends_left=0)))
        return out

    @property
    def invariants(self) -> List[Invariant]:
        def no_lost_acked(s: JournalState) -> bool:
            durable = set(self._durable_order(s))
            return all(e in durable or e in s.retired for e in s.acked)

        def seq_monotone(s: JournalState) -> bool:
            last: Dict[str, int] = {}
            for comp, seq in self._durable_order(s):
                if comp in last and seq <= last[comp]:
                    return False
                last[comp] = seq
            return True

        return [
            Invariant(
                "no_lost_acked_event",
                "every acked event is durable (flushed segment) or was "
                "retired by retention after being durable — never "
                "sitting in a volatile buffer a crash can take",
                no_lost_acked),
            Invariant(
                "per_component_seq_monotone",
                "the durable replay order (closed segments oldest-"
                "first, then the active segment) carries strictly "
                "increasing seq per component — the property the "
                "journal auditor checks on real artifacts",
                seq_monotone),
            Invariant(
                "rotation_never_drops_unflushed",
                "no event is ever lost without a crash: rotation "
                "flushes the active tail before closing, and retention "
                "only deletes closed (fully durable) segments",
                lambda s: not s.lost or s.crashed),
        ]


SPECS: Dict[str, type] = {
    "cycle": CycleSpec,
    "epoch": EpochSpec,
    "drain": DrainSpec,
    "tune": TuneSpec,
    "autoscale": AutoscaleSpec,
    "paged_cache": PagedCacheSpec,
    "scrape": ScrapeSpec,
    "replica": ReplicaSpec,
    "journal": JournalSpec,
}

# mutant name -> (spec name, constructor kwarg, description). Each is a
# seeded historical bug (or a deliberate weakening proving an invariant
# has teeth); `hvd-check --mutant <name>` must find a counterexample.
MUTANTS: Dict[str, Tuple[str, str, str]] = {
    "drain_scan_after_refresh": (
        "drain", "scan_after_refresh",
        "PR-9 same-heartbeat drain race: the heartbeat refreshed "
        "discovery before scanning drain keys, so a rebalance could "
        "place onto a host whose drain was already announced"),
    "drain_no_last_chance": (
        "drain", "no_last_chance",
        "PR-9 satellite: without the reap-time last-chance KV/registry "
        "check, a fast drain's exit 0 reads as job completion"),
    "drain_no_buddy": (
        "drain", "no_buddy",
        "commit-time ring-buddy replication removed: a kill between "
        "commit and handoff loses the acknowledged shard"),
    "epoch_accept_stale_notify": (
        "epoch", "accept_stale_notify",
        "PR-10 bug: a worker without the epoch floor accepts a "
        "fenced-out pre-crash driver's stale notify and resets backward "
        "into an older generation"),
    "epoch_no_fence": (
        "epoch", "no_fence",
        "KV-side 409 fencing removed: a lingering older-epoch driver's "
        "mutation lands after the recovered driver's (split-brain)"),
    "epoch_no_adoption_check": (
        "epoch", "no_adoption_check",
        "driver recovery spawns every expected slot without the "
        "heartbeat adoption check: live workers get double-spawned"),
    "cycle_rank_divergent_express": (
        "cycle", "rank_divergent_express",
        "rank-divergent express-lane partition (serving-mode hazard "
        "class): ranks peel different response sets onto the express "
        "lane and execute collectives in different orders"),
    "cycle_abort_ignored": (
        "cycle", "ignore_abort",
        "fast-abort flag dropped from the coordination word: a crash or "
        "hvdtpu_abort signal is never honored and cycles keep "
        "negotiating past it"),
    "tune_apply_inline": (
        "tune", "apply_inline",
        "TunedParams applied inline at push instead of staged for the "
        "cycle-boundary broadcast: the coordinator runs different knobs "
        "than its peers mid-cycle"),
    "tune_env_divergent_routing": (
        "tune", "env_divergent_routing",
        "pre-ABI-10 data-plane routing: a rank seeds its ring threshold "
        "from its own HOROVOD_RING_THRESHOLD_BYTES instead of the "
        "cycle-fenced TunedParams broadcast — two ranks route the same "
        "collective through different algorithms and deadlock"),
    "autoscale_no_hysteresis": (
        "autoscale", "no_hysteresis",
        "hysteresis windows removed: the policy decides on a single "
        "breached/idle observation, so a spike-then-dip flips the fleet "
        "in opposite directions inside one window (flapping)"),
    "autoscale_victim_draining": (
        "autoscale", "victim_draining",
        "scale-down victim selection stops excluding draining workers: "
        "the repeated preemption notice force-exits the already-draining "
        "victim (preempt.py) and its acked requests are lost"),
    "autoscale_stale_epoch_decision": (
        "autoscale", "no_epoch_fence",
        "KV epoch fence removed from autoscale decision writes: after "
        "driver recovery the lingering pre-crash driver applies its "
        "stale decision and the fleet resizes twice for one decision"),
    "paged_double_free_running_expiry": (
        "paged_cache", "double_free_running_expiry",
        "running-expiry teardown frees the request's charged blocks at "
        "the step boundary AND again at finish: the pool ledger "
        "over-credits and a later admission aliases live blocks"),
    "paged_stale_prefix_reuse": (
        "paged_cache", "stale_prefix_reuse",
        "admission consults the prefix hash table without re-checking "
        "residency: it increfs a shared block the LRU already evicted "
        "and the request decodes from a freed page (use-after-free)"),
    "scrape_double_count_on_fallback": (
        "scrape", "double_count_on_fallback",
        "the heartbeat's direct-fallback leg runs AFTER the aggregator "
        "leg in the same beat, both diffing the baseline read at "
        "heartbeat start: every relayed increment lands twice in the "
        "driver's totals"),
    "scrape_baseline_reset_skipped": (
        "scrape", "skip_baseline_reset",
        "PR-7 stale-baseline bug resurfacing through the aggregator "
        "tier: the generation change keeps metrics_prev, so the first "
        "post-rebalance heartbeat diffs a restarted rank against a "
        "dead incarnation's counters"),
    "replica_stale_lease_accepts_write": (
        "replica", "stale_lease_accepts_write",
        "the leader's write-path lease-expiry check removed: a slow "
        "(paused/partitioned) leader keeps accepting writes after its "
        "lease lapsed, so once a successor is elected two replicas "
        "accept writes at the same instant (split brain)"),
    "replica_election_without_majority": (
        "replica", "election_without_majority",
        "the election quorum check removed: a partitioned minority "
        "replica elects itself on its own vote, producing a second "
        "simultaneous leaseholder at a non-advancing epoch"),
    "replica_retry_double_apply": (
        "replica", "retry_double_apply",
        "the (client, seq) idempotency-token dedupe removed: a client "
        "retry after a timed-out-but-committed write re-appends the "
        "same op, which lands twice in every replica's WAL"),
    "journal_ack_before_flush": (
        "journal", "ack_before_flush",
        "journal append acks the emitter before the segment flush: a "
        "crash in the gap loses an event the caller was told is "
        "durable, so hvd-doctor's timeline silently misses the acked "
        "evidence"),
    "journal_seq_reset_on_rotate": (
        "journal", "seq_reset_on_rotate",
        "the per-writer sequence counter restarts at segment rotation: "
        "replayed seqs regress across the segment boundary and the "
        "journal auditor's per-component monotonicity (the doctor's "
        "tie-breaking order) is violated"),
    "journal_rotate_skip_flush": (
        "journal", "rotate_skip_flush",
        "rotation closes the active segment without flushing its "
        "buffered tail: records evaporate with no crash anywhere — the "
        "rotation-never-drops-an-unflushed-segment rule is violated"),
    "scrape_consume_stale_window": (
        "scrape", "consume_stale_window",
        "the per-host window floor removed: an age-fresh /agg.json "
        "payload sampled BEFORE the driver's last direct consume "
        "regresses the shared baseline, and the next window re-counts "
        "the difference"),
}


def make_spec(name: str, mutant: Optional[str] = None) -> Spec:
    """Instantiate a spec, optionally with one seeded mutation."""
    if mutant is not None:
        spec_name, kwarg, _ = MUTANTS[mutant]
        if name not in (None, spec_name):
            raise ValueError(f"mutant {mutant} belongs to spec "
                             f"{spec_name}, not {name}")
        return SPECS[spec_name](**{kwarg: True})
    return SPECS[name]()
