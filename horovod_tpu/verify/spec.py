"""Spec framework: states, actions, invariants.

A spec is an explicit-state transition system small enough to enumerate:

- ``initial()`` returns the (hashable) initial state;
- ``actions(state)`` returns the enabled transitions as
  ``[(label, successor_state), ...]`` — *every* nondeterministic choice
  (scheduling, message timing, fault injection) is an action, so the
  checker's enumeration of action interleavings IS the enumeration of
  executions;
- ``invariants`` are named safety predicates checked on every reachable
  state.

Fault injection is not a checker feature but a modeling convention:
specs carry budget counters in the state (``crashes_left`` etc.) and
expose crash/partition/drop transitions guarded by them, which makes
"faults injectable at every step" fall out of ordinary exploration.

States are ``NamedTuple``s: hashable (the visited set), immutable
(successors are fresh states), and cheap to render in counterexample
traces.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable, List, Tuple


@dataclass(frozen=True)
class Invariant:
    name: str
    doc: str
    check: Callable  # state -> bool; False = violated


@dataclass
class Spec:
    """Base class; concrete specs in ``horovod_tpu/verify/specs.py``."""

    name: str = "spec"
    mutations: Tuple[str, ...] = field(default_factory=tuple)

    def initial(self):
        raise NotImplementedError

    def actions(self, state) -> Iterable[Tuple[str, object]]:
        raise NotImplementedError

    @property
    def invariants(self) -> List[Invariant]:
        raise NotImplementedError
